"""First-class invariant catalog for the model checker.

Every invariant is a named predicate over either a *state* (evaluated on
the honest nodes materialized at that state, plus a fresh-observer union
replay) or an *edge* (the acting node before vs. after one transition —
monotonicity properties live here, because "decided fame never flips" is
a statement about consecutive views of ONE node, not about a single
snapshot).  The checker evaluates the full catalog at every explored
state/transition; a violation carries the invariant id, the offending
role, and a human-readable message, and becomes the seed of the
counterexample pipeline.

Catalog
-------
- ``prefix-agreement`` (state): any two honest nodes' decided orders
  agree on their common prefix — THE safety property.
- ``union-replay`` (state): each honest node's decided order is a
  prefix of a fresh observer's single-pass replay of the union of all
  honest views, and round/witness/fame metadata agree per event with
  that observer (purity of the consensus functions in the DAG).
- ``fame-once`` (edge): along every transition the acting node's
  per-event round, witness flag, witness slot, decided fame, receive
  round, and consensus timestamp never change once set, and the decided
  order only appends.
- ``round-sanity`` (state): rounds are monotone along parent edges,
  genesis rounds are 0, and no round exceeds ``max_round``.
- ``horizon`` (state): the expiry-horizon rule is sound — zero
  ``horizon_violations``, EVERY event satisfying the witness predicate
  is flagged and registered (``wit_slot`` / ``wit_list`` / ``witnesses``
  all agree), however late it arrived, and late registrations are a
  subset of registered witnesses.
- ``fork-budget`` (state): the fork ledger matches ground truth
  recomputed from ``by_seq`` (flagged creators are exactly those with a
  multi-event seq group, and only attacker members), the equivocation
  counter counts fork groups, and the 3f budget trips iff the number of
  forked creators exceeds ``f = (n-1)//3``.
- ``epoch-purity`` (state, dynamic membership): each honest node's epoch
  ledger equals the canonical reconstruction from its own decided prefix
  (canonical activation rule), and no recorded fame tally counted stake
  from any epoch other than the one governing its voting round.
- ``counter-consistency`` (state): over a reliable transport every
  pathology counter (bad replies/requests, retries, circuit opens,
  withholding, capped branches, quarantines) is zero and the orphan
  buffer is fully drained — nonzero means a protocol/codec bug, and a
  drained buffer is also what licenses the checker's state abstraction
  (ingest histories capture everything a node holds).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, NamedTuple, Optional

from tpu_swirld.oracle.node import Node

from tpu_swirld.analysis.mc.world import MCState, World


@dataclasses.dataclass(frozen=True)
class Violation:
    invariant: str
    role: Optional[int]
    message: str

    def to_dict(self) -> dict:
        return {
            "invariant": self.invariant,
            "role": self.role,
            "message": self.message,
        }


def _short(eid: bytes) -> str:
    return eid.hex()[:12]


# --------------------------------------------------------------- state


def _honest_nodes(world: World, state: MCState) -> Dict[int, Node]:
    return {
        i: world.node_for(i, state.histories[i])
        for i in world.honest_roles
    }


def check_prefix_agreement(world: World, state: MCState,
                           nodes: Dict[int, Node]) -> List[Violation]:
    out: List[Violation] = []
    roles = sorted(nodes)
    for a in roles:
        for b in roles:
            if b <= a:
                continue
            ca, cb = nodes[a].consensus, nodes[b].consensus
            m = min(len(ca), len(cb))
            for k in range(m):
                if ca[k] != cb[k]:
                    out.append(Violation(
                        "prefix-agreement", a,
                        f"honest {a} and {b} diverge at decided index {k}: "
                        f"{_short(ca[k])} vs {_short(cb[k])}",
                    ))
                    break
    return out


def check_union_replay(world: World, state: MCState,
                       nodes: Dict[int, Node]) -> List[Violation]:
    out: List[Violation] = []
    obs = world.union_observer(state)
    for i, node in nodes.items():
        co, cn = obs.consensus, node.consensus
        if cn != co[: len(cn)]:
            out.append(Violation(
                "union-replay", i,
                f"honest {i}'s decided order is not a prefix of the "
                f"union replay ({len(cn)} vs {len(co)} decided)",
            ))
            continue
        for eid in node.hg:
            if node.round.get(eid) != obs.round.get(eid):
                out.append(Violation(
                    "union-replay", i,
                    f"round disagrees with union replay on "
                    f"{_short(eid)}: {node.round.get(eid)} vs "
                    f"{obs.round.get(eid)}",
                ))
                break
            if node.is_witness.get(eid) != obs.is_witness.get(eid):
                out.append(Violation(
                    "union-replay", i,
                    f"witness flag disagrees with union replay on "
                    f"{_short(eid)}",
                ))
                break
            fn, fo = node.famous.get(eid), obs.famous.get(eid)
            if fn is not None and fo is not None and fn != fo:
                out.append(Violation(
                    "union-replay", i,
                    f"fame decided both ways on {_short(eid)}: "
                    f"{fn} here vs {fo} in union replay",
                ))
                break
    return out


def check_round_sanity(world: World, state: MCState,
                       nodes: Dict[int, Node]) -> List[Violation]:
    out: List[Violation] = []
    for i, node in nodes.items():
        for eid, ev in node.hg.items():
            r = node.round.get(eid)
            if not ev.p:
                if r != 0:
                    out.append(Violation(
                        "round-sanity", i,
                        f"genesis {_short(eid)} has round {r} != 0",
                    ))
                continue
            pr = max(node.round[p] for p in ev.p)
            if r is None or r < pr:
                out.append(Violation(
                    "round-sanity", i,
                    f"round not monotone at {_short(eid)}: round {r} < "
                    f"max parent round {pr}",
                ))
            if r is not None and r > node.max_round:
                out.append(Violation(
                    "round-sanity", i,
                    f"round {r} of {_short(eid)} exceeds max_round "
                    f"{node.max_round}",
                ))
    return out


def _witness_predicate(node: Node, eid: bytes) -> bool:
    ev = node.hg[eid]
    if not ev.p:
        return True
    return node.round[ev.p[0]] < node.round[eid]


def check_horizon(world: World, state: MCState,
                  nodes: Dict[int, Node]) -> List[Violation]:
    out: List[Violation] = []
    for i, node in nodes.items():
        if node.horizon_violations:
            out.append(Violation(
                "horizon", i,
                f"{node.horizon_violations} late witness(es) decided "
                f"famous — expiry horizon unsound",
            ))
        for eid in node.hg:
            pred = _witness_predicate(node, eid)
            flag = node.is_witness.get(eid, False)
            if pred != flag:
                out.append(Violation(
                    "horizon", i,
                    f"witness flag wrong on {_short(eid)}: predicate "
                    f"{pred} but flagged {flag} (late/low-round events "
                    f"must still register)",
                ))
                continue
            if pred:
                r = node.round[eid]
                slot = node.wit_slot.get(eid)
                lst = node.wit_list.get(r, [])
                if (
                    slot is None
                    or slot >= len(lst)
                    or lst[slot] != eid
                    or eid not in node.witnesses.get(r, {}).get(
                        node.hg[eid].c, [])
                ):
                    out.append(Violation(
                        "horizon", i,
                        f"witness {_short(eid)} (round {r}) not "
                        f"registered in wit_slot/wit_list/witnesses — "
                        f"a quarantined witness breaks node agreement",
                    ))
        for eid in node.late_witnesses:
            if eid not in node.wit_slot:
                out.append(Violation(
                    "horizon", i,
                    f"late witness {_short(eid)} missing from wit_slot",
                ))
    return out


def check_fork_budget(world: World, state: MCState,
                      nodes: Dict[int, Node]) -> List[Violation]:
    out: List[Violation] = []
    f_budget = (len(world.members) - 1) // 3
    for i, node in nodes.items():
        truth_groups = 0
        truth_forked = set()
        for m in world.members:
            groups = [
                g for g in node.by_seq[m].values() if len(g) >= 2
            ]
            truth_groups += len(groups)
            if groups:
                truth_forked.add(m)
            if node.has_fork[m] != bool(groups):
                out.append(Violation(
                    "fork-budget", i,
                    f"fork ledger wrong for member "
                    f"{world.members.index(m)}: by_seq shows "
                    f"{len(groups)} fork group(s) but has_fork is "
                    f"{node.has_fork[m]}",
                ))
        bad = truth_forked - set(world.byz_members)
        if bad:
            out.append(Violation(
                "fork-budget", i,
                f"honest member(s) {sorted(world.members.index(m) for m in bad)} "
                f"appear forked — honest chains must be linear",
            ))
        if node.forks_detected != len(truth_forked):
            out.append(Violation(
                "fork-budget", i,
                f"forks_detected={node.forks_detected} but "
                f"{len(truth_forked)} creator(s) actually forked",
            ))
        if node.equivocations_detected != truth_groups:
            out.append(Violation(
                "fork-budget", i,
                f"equivocations_detected={node.equivocations_detected} "
                f"but {truth_groups} fork group(s) exist",
            ))
        tripped = node.budget_exhausted > 0
        should = len(truth_forked) > f_budget
        if tripped != should:
            out.append(Violation(
                "fork-budget", i,
                f"3f budget accounting wrong: {len(truth_forked)} forked "
                f"creator(s) vs f={f_budget}, but budget_exhausted="
                f"{node.budget_exhausted}",
            ))
    return out


def check_epoch_purity(world: World, state: MCState,
                       nodes: Dict[int, Node]) -> List[Violation]:
    """Dynamic membership: (1) each honest node's epoch ledger equals the
    canonical reconstruction from its own decided prefix through the
    canonical activation rule — any skew in the node's incremental
    adoption path (e.g. an off-by-one activation round) is a detectable
    divergence; (2) every recorded fame tally counted stake from exactly
    the epoch governing its voting round — no decision mixes stake from
    two epochs.  Vacuous for static-membership worlds."""
    from tpu_swirld.membership.epoch import (
        DEFAULT_DELAY, ledger_from_decided,
    )

    out: List[Violation] = []
    for i, node in nodes.items():
        ledger = getattr(node, "ledger", None)
        if ledger is None:
            return []
        delay = getattr(node, "membership_delay", DEFAULT_DELAY)
        canon = ledger_from_decided(
            (
                (x, node.hg[x].d, node.round_received[x])
                for x in node.consensus
            ),
            node._genesis_members, node._genesis_stake, delay,
        )
        if not canon.same_epochs(ledger):
            got = [
                (e.epoch_id, e.activation_round, e.stake)
                for e in ledger.epochs
            ]
            want = [
                (e.epoch_id, e.activation_round, e.stake)
                for e in canon.epochs
            ]
            out.append(Violation(
                "epoch-purity", i,
                f"honest {i}'s epoch ledger diverges from the canonical "
                f"reconstruction of its decided prefix: {got} vs "
                f"canonical {want}",
            ))
            continue
        for x, ry, tallied in getattr(node, "fame_epoch_log", []):
            governing = ledger.epoch_at(ry - 1).epoch_id
            if tallied != governing:
                out.append(Violation(
                    "epoch-purity", i,
                    f"fame of {_short(x)} tallied at voting round {ry} "
                    f"with epoch {tallied} stake but epoch {governing} "
                    f"governs round {ry - 1} — decision mixes epochs",
                ))
                break
    return out


def check_counters(world: World, state: MCState,
                   nodes: Dict[int, Node]) -> List[Violation]:
    out: List[Violation] = []
    for i, node in nodes.items():
        for name in (
            "bad_replies", "bad_requests", "retries",
            "withholding_suspected", "sync_branches_capped",
            "orphans_parked",
        ):
            v = getattr(node, name)
            if v:
                out.append(Violation(
                    "counter-consistency", i,
                    f"{name}={v} on honest {i} over a reliable "
                    f"transport — protocol/codec bug (and a parked "
                    f"orphan would break the history abstraction)",
                ))
        # breaker activity is legitimate EXACTLY when the fork machinery
        # drove it: a proven (or over-budget) equivocator is cut off by
        # design even with quarantine_forkers off.  Every quarantined
        # peer must therefore be a detected-forked byzantine creator,
        # and with no forks detected the breaker must be silent.
        justified = {
            c for c, forked in node.has_fork.items()
            if forked and c in world.byz_members and c != node.pk
        }
        quarantined = (
            set(node.breaker.quarantined()) if node.breaker else set()
        )
        if not quarantined <= justified:
            out.append(Violation(
                "counter-consistency", i,
                f"honest {i} quarantined {len(quarantined - justified)} "
                f"peer(s) with no detected fork to justify the cut",
            ))
        open_budget = node.equivocations_detected + node.forks_detected
        if node.circuit_opens > open_budget:
            out.append(Violation(
                "counter-consistency", i,
                f"circuit_opens={node.circuit_opens} on honest {i} "
                f"exceeds the fork-machinery budget {open_budget} — the "
                f"breaker fired on honest traffic",
            ))
    return out


# ---------------------------------------------------------------- edge


def check_fame_once(world: World, action: tuple,
                    parent: Node, child: Node) -> List[Violation]:
    """Monotonicity of the acting node across one transition."""
    out: List[Violation] = []
    role = action[1]

    def bad(msg: str) -> None:
        out.append(Violation("fame-once", role, msg))

    if child.consensus[: len(parent.consensus)] != parent.consensus:
        bad(
            f"decided order rewrote itself across {action!r}: "
            f"{len(parent.consensus)} decided before, prefix differs after"
        )
    for eid, f in parent.famous.items():
        if f is not None and child.famous.get(eid) != f:
            bad(
                f"fame of {_short(eid)} decided twice: {f} then "
                f"{child.famous.get(eid)} across {action!r}"
            )
    for attr in ("round", "is_witness", "wit_slot",
                 "round_received", "consensus_ts"):
        pa, ch = getattr(parent, attr), getattr(child, attr)
        for eid, v in pa.items():
            if eid in ch and ch[eid] != v:
                bad(
                    f"{attr}[{_short(eid)}] changed {v} -> {ch[eid]} "
                    f"across {action!r}"
                )
                break
    return out


# ------------------------------------------------------------- catalog


class Invariant(NamedTuple):
    id: str
    kind: str          # "state" | "edge"
    fn: Callable
    describe: str


# Catalog order matters for reporting: ``check_state`` returns
# violations in this order, and the explorer surfaces the FIRST one —
# so local, single-node diagnoses (a wrong round, a missing witness
# flag, a fork-ledger mismatch) come before the global agreement
# invariants, which almost any local bug eventually also trips.
INVARIANTS: List[Invariant] = [
    Invariant("round-sanity", "state", check_round_sanity,
              "rounds are parent-monotone, geneses are round 0, nothing "
              "exceeds max_round"),
    Invariant("horizon", "state", check_horizon,
              "expiry horizon sound: every witness-predicate event is "
              "flagged and registered however late it arrives"),
    Invariant("fork-budget", "state", check_fork_budget,
              "fork ledger == ground truth from by_seq; 3f budget trips "
              "iff forked creators exceed f"),
    Invariant("epoch-purity", "state", check_epoch_purity,
              "epoch ledger equals the canonical reconstruction from the "
              "decided prefix; no fame tally mixes stake from two epochs"),
    Invariant("counter-consistency", "state", check_counters,
              "all pathology counters zero and orphans drained over a "
              "reliable transport"),
    Invariant("fame-once", "edge", check_fame_once,
              "per-event consensus metadata is write-once and the decided "
              "order append-only along every transition"),
    Invariant("prefix-agreement", "state", check_prefix_agreement,
              "honest decided orders agree on their common prefix"),
    Invariant("union-replay", "state", check_union_replay,
              "each honest order is a prefix of the fresh-observer union "
              "replay; round/witness/fame metadata agree per event"),
]


def catalog() -> List[Invariant]:
    return list(INVARIANTS)


def check_state(world: World, state: MCState) -> List[Violation]:
    nodes = _honest_nodes(world, state)
    out: List[Violation] = []
    for inv in INVARIANTS:
        if inv.kind == "state":
            out.extend(inv.fn(world, state, nodes))
    return out


def check_edge(world: World, action: tuple,
               parent: Node, child: Node) -> List[Violation]:
    out: List[Violation] = []
    if world.roles[action[1]].kind != "honest":
        return out
    for inv in INVARIANTS:
        if inv.kind == "edge":
            out.extend(inv.fn(world, action, parent, child))
    return out
