"""Seeded-bug mutations: prove each invariant catches its regression.

A mutation is a small :class:`~tpu_swirld.oracle.node.Node` subclass
that re-introduces a realistic consensus bug through one of the seams
the production node exposes (``_parent_round``, ``_on_fork_group``,
``_check_fork_budget``, ``_register_witness``).  Mutations apply to the
HONEST nodes only — attacker branches stay vanilla, so the checker is
demonstrating that a buggy implementation is caught, not that a buggy
adversary misbehaves.

Each mutation names the invariant expected to fire and ships a default
world sized so the hunt finds a witness in seconds; the CLI
(``--mutate <name>``) then minimizes the witness and proves the
minimized counterexample still reproduces the same violation through a
deterministic replay.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List

from tpu_swirld.config import SwirldConfig
from tpu_swirld.oracle.node import Node

from tpu_swirld.analysis.mc.world import World


@dataclasses.dataclass(frozen=True)
class Mutation:
    name: str
    expected_invariant: str
    describe: str
    #: default world shape where the bug is reachable quickly
    world_kwargs: dict
    make_node_cls: Callable[[], type]


def _round_skew_cls() -> type:
    class RoundSkewNode(Node):
        """Base round = MIN of parent rounds — the classic copy-paste
        regression; rounds stop being monotone along parent edges.

        Witness promotion masks a one-round skew (an event whose
        ancestry contains a round-r parent strongly sees that round's
        witnesses, so the +1 promotion heals ``min`` back to ``max``
        whenever parents differ by one round), which is exactly why
        this bug survives casual testing: it only bites when a laggard
        with a round-0 self-parent ingests a round-2+ other-parent.
        The default world makes that reachable in 5 events by weighting
        stakes (2,2,1) — the two heavy members ladder to round 2 in a
        4-event gossip ladder while the light member lags at its
        genesis, and the light member's first sync trips the skew."""

        def _parent_round(self, sp: bytes, op: bytes) -> int:
            return min(self.round[sp], self.round[op])

    return RoundSkewNode


def _fork_blind_cls() -> type:
    class ForkBlindNode(Node):
        """Never records fork groups: the equivocation ledger stays
        empty while ``by_seq`` plainly shows the fork pair."""

        def _on_fork_group(self, c: bytes, s: int, group: List[bytes]) -> None:
            pass

    return ForkBlindNode


def _disable_fork_budget_cls() -> type:
    class NoBudgetNode(Node):
        """Fork ledger intact but the 3f budget check is compiled out —
        more than f forked creators never trips ``budget_exhausted``."""

        def _check_fork_budget(self, c: bytes) -> None:
            pass

    return NoBudgetNode


def _dynamic_node_cls() -> type:
    from tpu_swirld.membership.dynamic import DynamicNode

    return DynamicNode


def _epoch_skew_cls() -> type:
    from tpu_swirld.membership.dynamic import DynamicNode

    class EpochSkewNode(DynamicNode):
        """Epoch activation off by one round: a decided membership tx
        takes effect one round later than the canonical rule — every
        honest node still *agrees* (the bug is deterministic), which is
        exactly why prefix-agreement can't catch it; only the epoch-
        purity invariant's canonical reconstruction does."""

        def _activation_round(self, round_received: int) -> int:
            return super()._activation_round(round_received) + 1

    return EpochSkewNode


def _skip_horizon_cls() -> type:
    class SkipHorizonNode(Node):
        """Quarantines witnesses that land below the node's current
        progress (the pre-horizon-rule bug shape): a straggler genesis
        arriving after this node reached round 1 is silently dropped
        from the witness registry, so peers with different arrival
        orders disagree."""

        def _register_witness(self, eid: bytes, r: int) -> None:
            if r < self.max_round:
                self.is_witness[eid] = False
                return
            super()._register_witness(eid, r)

    return SkipHorizonNode


MUTATIONS: Dict[str, Mutation] = {
    m.name: m
    for m in [
        Mutation(
            name="round-skew",
            expected_invariant="round-sanity",
            describe="base round = min(parent rounds) instead of max",
            world_kwargs=dict(
                n_honest=3, n_forkers=0, events=5,
                config=SwirldConfig(n_members=3, stake=(2, 2, 1)),
            ),
            make_node_cls=_round_skew_cls,
        ),
        Mutation(
            name="fork-blind",
            expected_invariant="fork-budget",
            describe="fork groups never recorded in the ledger",
            world_kwargs=dict(n_honest=2, n_forkers=1, events=3),
            make_node_cls=_fork_blind_cls,
        ),
        Mutation(
            name="disable-fork-budget",
            expected_invariant="fork-budget",
            describe="3f fork-budget check compiled out",
            # budget 6: exceeding f=1 forked creators needs BOTH forkers'
            # fork pairs visible at one honest node, and the sync height
            # hint only ships a sibling branch when branch lengths are
            # asymmetric (equal counts cancel the delta) — so each fork
            # costs three events: two on one branch, one on the other
            world_kwargs=dict(n_honest=2, n_forkers=2, events=6),
            make_node_cls=_disable_fork_budget_cls,
        ),
        Mutation(
            name="epoch-skew",
            expected_invariant="epoch-purity",
            describe="membership-tx activation round off by one",
            # a restake tx rides member 0's genesis; the ledger diverges
            # from the canonical reconstruction the moment the genesis
            # decides (~23 events in a 3-member gossip ladder) — budget
            # 30 leaves the weighted hunt slack for non-ladder detours
            world_kwargs=dict(
                n_honest=3, n_forkers=0, events=30,
                genesis_mtx={0: ("restake", 1, 3)},
                observer_cls=_dynamic_node_cls(),
            ),
            make_node_cls=_epoch_skew_cls,
        ),
        Mutation(
            name="skip-horizon",
            expected_invariant="horizon",
            describe="witnesses below current max_round quarantined "
                     "instead of registered",
            world_kwargs=dict(n_honest=4, n_forkers=0, events=4),
            make_node_cls=_skip_horizon_cls,
        ),
    ]
}


def make_world(mutate: str = None, **overrides) -> World:
    """World factory: vanilla when ``mutate`` is None, else the
    mutation's default shape (overridable) with its node class."""
    if mutate is None:
        return World(**overrides)
    mut = MUTATIONS[mutate]
    kw = dict(mut.world_kwargs)
    kw.update(overrides)
    return World(node_cls=mut.make_node_cls(), **kw)
