"""Exhaustive-schedule model checker for the consensus core.

Explicit-state exploration of ALL reachable gossip/delivery
interleavings of a small world (n <= 4 honest members plus attacker
fork branches, depth-bounded by an event budget), driving the real
``oracle.node.Node`` + ``Transport`` seam rather than a re-model.  See
the module docstrings for the moving parts:

- :mod:`world` — states as per-role ingest histories; actions over the
  real pull/sync path; deterministic branch extensions; live schedule
  replay.
- :mod:`encode` — canonical state keys: hashed dedup plus honest-member
  symmetry reduction.
- :mod:`explore` — exhaustive BFS proof with sleep-set partial-order
  reduction, the naive baseline for reduction ratios, and the seeded
  random-walk violation hunt used by mutation runs.
- :mod:`invariants` — the first-class invariant catalog.
- :mod:`mutations` — seeded bugs proving each invariant bites.
- :mod:`counterexample` — ddmin minimization and bit-deterministic
  replayable JSON documents.
- :mod:`cli` — the ``python -m tpu_swirld.analysis mc`` front end.
"""

from tpu_swirld.analysis.mc.cli import main, mc_smoke, run_mc
from tpu_swirld.analysis.mc.counterexample import replay, run_checked
from tpu_swirld.analysis.mc.explore import ExploreResult, explore, hunt
from tpu_swirld.analysis.mc.invariants import INVARIANTS, Violation, catalog
from tpu_swirld.analysis.mc.mutations import MUTATIONS, make_world
from tpu_swirld.analysis.mc.world import MCState, World

__all__ = [
    "ExploreResult", "INVARIANTS", "MCState", "MUTATIONS", "Violation",
    "World", "catalog", "explore", "hunt", "main", "make_world",
    "mc_smoke", "replay", "run_checked", "run_mc",
]
