"""Interval × dtype lattice for the scale-envelope flow analysis.

The abstract domain is deliberately *whole-array*: one interval per
jaxpr variable, covering every element the array can hold.  Shapes and
dtypes ride along exactly (they come for free from the traced avals),
so the only thing this module approximates is the **value range**.
That is enough to prove the properties the audit cares about — "no
int32 in this kernel can exceed 2**31-1 at the 1M envelope" is a
statement about the max over all elements, which is exactly what a
whole-array interval bounds.

Two refinements beyond a plain interval:

- ``integral``: True when every element is known to be integer-valued
  *even if the dtype is floating*.  The pipeline's f32 GEMM tally path
  is sound only because integer-valued f32 sums stay exact below 2**24;
  the flag lets :mod:`.transfer` check that argument instead of
  drowning the float path in false positives.
- interval endpoints are plain Python ints/floats (arbitrary precision
  for ints), so overflow detection compares the *mathematical* result
  against the dtype range — the analysis itself cannot wrap.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Tuple

import numpy as np

NEG_INF = float("-inf")
POS_INF = float("inf")


def dtype_range(dtype) -> Tuple[Any, Any]:
    """Representable [lo, hi] for a dtype (inf for floats' finite range)."""
    dt = np.dtype(dtype)
    if dt.kind in "iu":
        info = np.iinfo(dt)
        return int(info.min), int(info.max)
    if dt.kind == "b":
        return 0, 1
    if dt.kind == "f":
        info = np.finfo(dt)
        return float(info.min), float(info.max)
    raise ValueError(f"unsupported dtype for interval analysis: {dt!r}")


def is_int_dtype(dtype) -> bool:
    return np.dtype(dtype).kind in "iu"


def is_bool_dtype(dtype) -> bool:
    return np.dtype(dtype).kind == "b"


def is_float_dtype(dtype) -> bool:
    return np.dtype(dtype).kind == "f"


@dataclasses.dataclass(frozen=True)
class Interval:
    """Closed interval [lo, hi] over exact Python numbers.

    ``lo``/``hi`` are ints when the producing dtype is integral (exact,
    unbounded) and floats otherwise; ±inf endpoints mean "unbounded".
    An empty interval is represented by lo > hi and normally only
    appears transiently (e.g. a branch proven dead); joins treat it as
    bottom.
    """

    lo: Any
    hi: Any

    @staticmethod
    def bottom() -> "Interval":
        return Interval(POS_INF, NEG_INF)

    @staticmethod
    def point(v) -> "Interval":
        return Interval(v, v)

    @property
    def is_bottom(self) -> bool:
        return self.lo > self.hi

    @property
    def is_point(self) -> bool:
        return self.lo == self.hi

    def join(self, other: "Interval") -> "Interval":
        if self.is_bottom:
            return other
        if other.is_bottom:
            return self
        return Interval(min(self.lo, other.lo), max(self.hi, other.hi))

    def meet(self, other: "Interval") -> "Interval":
        return Interval(max(self.lo, other.lo), min(self.hi, other.hi))

    def contains(self, v) -> bool:
        return (not self.is_bottom) and self.lo <= v <= self.hi

    def covers(self, other: "Interval") -> bool:
        if other.is_bottom:
            return True
        if self.is_bottom:
            return False
        return self.lo <= other.lo and self.hi >= other.hi

    def shift(self, k) -> "Interval":
        if self.is_bottom:
            return self
        return Interval(self.lo + k, self.hi + k)

    def __repr__(self) -> str:  # compact in findings
        if self.is_bottom:
            return "[⊥]"

        def f(v):
            if v == POS_INF:
                return "+inf"
            if v == NEG_INF:
                return "-inf"
            if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
                return str(int(v))
            return str(v)

        return f"[{f(self.lo)}, {f(self.hi)}]"


# Arithmetic on intervals.  All helpers are total: ±inf endpoints are
# legal, and 0 * inf is resolved to 0 (the convention that keeps
# multiplication monotone for our use: a zero factor bounds the product
# at zero no matter how wild the other side is).


def _mul(a, b):
    if (a == 0 or b == 0) and (
        a in (POS_INF, NEG_INF) or b in (POS_INF, NEG_INF)
    ):
        return 0
    return a * b


def iv_add(a: Interval, b: Interval) -> Interval:
    if a.is_bottom or b.is_bottom:
        return Interval.bottom()
    return Interval(a.lo + b.lo, a.hi + b.hi)


def iv_sub(a: Interval, b: Interval) -> Interval:
    if a.is_bottom or b.is_bottom:
        return Interval.bottom()
    return Interval(a.lo - b.hi, a.hi - b.lo)


def iv_neg(a: Interval) -> Interval:
    if a.is_bottom:
        return a
    return Interval(-a.hi, -a.lo)


def iv_mul(a: Interval, b: Interval) -> Interval:
    if a.is_bottom or b.is_bottom:
        return Interval.bottom()
    cands = [_mul(a.lo, b.lo), _mul(a.lo, b.hi), _mul(a.hi, b.lo), _mul(a.hi, b.hi)]
    return Interval(min(cands), max(cands))


def iv_min(a: Interval, b: Interval) -> Interval:
    if a.is_bottom or b.is_bottom:
        return Interval.bottom()
    return Interval(min(a.lo, b.lo), min(a.hi, b.hi))


def iv_max(a: Interval, b: Interval) -> Interval:
    if a.is_bottom or b.is_bottom:
        return Interval.bottom()
    return Interval(max(a.lo, b.lo), max(a.hi, b.hi))


def iv_abs(a: Interval) -> Interval:
    if a.is_bottom:
        return a
    if a.lo >= 0:
        return a
    if a.hi <= 0:
        return iv_neg(a)
    return Interval(0, max(-a.lo, a.hi))


def iv_div_int(a: Interval, b: Interval) -> Interval:
    """Integer (truncating) division; conservative when b spans 0."""
    if a.is_bottom or b.is_bottom:
        return Interval.bottom()
    if b.lo <= 0 <= b.hi:
        # A divisor interval containing 0: the quotient magnitude is
        # bounded by |a| (|b| >= 1 on the int lattice away from 0), so
        # fall back to the symmetric hull of a.
        m = max(abs(a.lo), abs(a.hi))
        return Interval(-m, m)
    cands = []
    for x in (a.lo, a.hi):
        for y in (b.lo, b.hi):
            if x in (POS_INF, NEG_INF) or y in (POS_INF, NEG_INF):
                cands.append(0 if x == 0 else (POS_INF if (x > 0) == (y > 0) else NEG_INF))
            else:
                cands.append(int(math.trunc(x / y)) if y != 0 else 0)
    return Interval(min(cands), max(cands))


def iv_div_float(a: Interval, b: Interval) -> Interval:
    if a.is_bottom or b.is_bottom:
        return Interval.bottom()
    if b.lo <= 0 <= b.hi:
        return Interval(NEG_INF, POS_INF)
    cands = []
    for x in (a.lo, a.hi):
        for y in (b.lo, b.hi):
            try:
                cands.append(x / y)
            except (ZeroDivisionError, OverflowError):
                return Interval(NEG_INF, POS_INF)
    return Interval(min(cands), max(cands))


def iv_rem(a: Interval, b: Interval) -> Interval:
    """lax.rem: sign follows the dividend (C semantics)."""
    if a.is_bottom or b.is_bottom:
        return Interval.bottom()
    m = max(abs(b.lo), abs(b.hi))
    if m in (POS_INF,):
        hi = a.hi if a.hi > 0 else 0
        lo = a.lo if a.lo < 0 else 0
        return Interval(lo, hi)
    m = int(m) if not isinstance(m, float) or m == int(m) else m
    bound = m - 1 if m >= 1 else 0
    lo = -bound if a.lo < 0 else 0
    hi = bound if a.hi > 0 else 0
    # |a % b| <= |a| as well
    lo = max(lo, a.lo if a.lo > NEG_INF else lo)
    hi = min(hi, a.hi if a.hi < POS_INF else hi)
    if lo > hi:
        lo, hi = min(0, lo), max(0, hi)
    return Interval(lo, hi)


@dataclasses.dataclass(frozen=True)
class AbsVal:
    """Abstract value: shape/dtype (exact, from the aval) + interval.

    ``integral`` tracks "every element is integer-valued", which stays
    meaningful for float dtypes (the f32 tally exactness argument).
    For int/bool dtypes it is True by construction.
    """

    shape: Tuple[int, ...]
    dtype: Any
    iv: Interval
    integral: bool = True

    @staticmethod
    def from_aval(aval, iv: Optional[Interval] = None, integral: Optional[bool] = None) -> "AbsVal":
        dt = np.dtype(aval.dtype)
        if iv is None:
            lo, hi = dtype_range(dt)
            iv = Interval(lo, hi)
        if integral is None:
            integral = dt.kind in "iub"
        return AbsVal(tuple(aval.shape), dt, iv, bool(integral))

    @staticmethod
    def from_literal(val) -> "AbsVal":
        arr = np.asarray(val)
        if arr.size == 0:
            return AbsVal(tuple(arr.shape), arr.dtype, Interval.bottom(), True)
        lo = arr.min()
        hi = arr.max()
        if arr.dtype.kind in "iub":
            lo, hi = int(lo), int(hi)
            integral = True
        else:
            lo, hi = float(lo), float(hi)
            integral = bool(np.all(arr == np.trunc(arr)))
        return AbsVal(tuple(arr.shape), arr.dtype, Interval(lo, hi), integral)

    @property
    def size(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    def with_iv(self, iv: Interval, integral: Optional[bool] = None) -> "AbsVal":
        return AbsVal(self.shape, self.dtype, iv,
                      self.integral if integral is None else bool(integral))

    def top_like(self) -> "AbsVal":
        lo, hi = dtype_range(self.dtype)
        return AbsVal(self.shape, self.dtype, Interval(lo, hi),
                      np.dtype(self.dtype).kind in "iub")

    def join(self, other: "AbsVal") -> "AbsVal":
        assert self.shape == other.shape and self.dtype == other.dtype, (
            f"join across shapes/dtypes: {self} vs {other}")
        return AbsVal(self.shape, self.dtype, self.iv.join(other.iv),
                      self.integral and other.integral)

    def covers(self, other: "AbsVal") -> bool:
        # self ⊒ other: the interval must contain other's, and if self
        # still claims integrality (the stronger fact) other must too.
        return self.iv.covers(other.iv) and (not self.integral or other.integral)

    def clamp_to_dtype(self) -> "AbsVal":
        lo, hi = dtype_range(self.dtype)
        return self.with_iv(self.iv.meet(Interval(lo, hi)))

    def __repr__(self) -> str:
        integ = "i" if self.integral and np.dtype(self.dtype).kind == "f" else ""
        return f"{np.dtype(self.dtype).name}{list(self.shape)}{integ}{self.iv}"


def join_or(a: Optional[AbsVal], b: AbsVal) -> AbsVal:
    return b if a is None else a.join(b)
