"""Scale-envelope abstract interpreter: jaxpr-level interval/dtype flow.

The linter (PR 9) sees syntax and the model checker (PR 11) sees tiny
worlds; neither can answer the question ROADMAP item 4 forces at 1M
events: *can any int32 in the compiled kernels wrap, any gather read out
of bounds, any narrowing lose a value, any padding sentinel collide with
live data — at the shapes and magnitudes the full-scale run actually
reaches?*  This package answers it with machine-checked value flow over
the **real compiled artifact**:

- every jitted consensus stage is traced to its jaxpr with
  ``jax.make_jaxpr`` at the declared **scale envelope** shapes (events,
  members, window, round/fork caps — :mod:`.envelope`), so the analysis
  covers exactly the program XLA compiles, not a guessed AST;
- an **interval × dtype lattice** (:mod:`.lattice`) is propagated
  through every primitive by a transfer-function registry
  (:mod:`.transfer`) that **hard-fails on unknown primitives** — there
  is no silent "assume top" unsoundness path;
- the interpreter (:mod:`.interpret`) handles the higher-order
  primitives the pipeline uses (``pjit``, ``scan``, ``while``, ``cond``,
  ``shard_map``) by sub-interpretation: carried loop state is solved by
  join-to-fixpoint, exact unrolling for short loops, and length-aware
  extent extrapolation for event-scale scans (a round counter over 1M
  events proves *rounds ≤ events*, which is the whole envelope
  argument for int32);
- violations become findings in the lint catalog's format and rule
  space — **SW008** overflow-reachable, **SW009** unproven gather/
  scatter/slice bounds, **SW010** lossy narrowing, **SW011** sentinel
  collision — pinpointed to file/line via the jaxpr's source info, and
  suppressible per site with ``# swirld-lint: disable=SW00x -- <why>``
  where the justification text is *required* (an unjustified
  suppression still fails the audit).

CLI::

    python -m tpu_swirld.analysis scale-audit --envelope 1m
    python -m tpu_swirld.analysis scale-audit --engine mesh --json
    python -m tpu_swirld.analysis scale-audit --mutate ssm-acc-int16

Exit codes: 0 proven clean, 1 findings, 2 unknown primitive (the
registry refused to guess).
"""

from tpu_swirld.analysis.flow.lattice import AbsVal, Interval  # noqa: F401
from tpu_swirld.analysis.flow.transfer import (  # noqa: F401
    UnknownPrimitiveError,
    registered_primitives,
)
from tpu_swirld.analysis.flow.interpret import interpret_jaxpr  # noqa: F401
from tpu_swirld.analysis.flow.envelope import ScaleEnvelope  # noqa: F401
from tpu_swirld.analysis.flow.audit import scale_audit, scale_audit_stamp  # noqa: F401

__all__ = [
    "AbsVal",
    "Interval",
    "UnknownPrimitiveError",
    "registered_primitives",
    "interpret_jaxpr",
    "ScaleEnvelope",
    "scale_audit",
    "scale_audit_stamp",
]
