"""Jaxpr interpreter over the interval×dtype lattice.

:func:`interpret_jaxpr` walks a ``ClosedJaxpr`` produced by
``jax.make_jaxpr`` at envelope shapes and computes an :class:`AbsVal`
per variable, dispatching first-order primitives through
:mod:`.transfer` and sub-interpreting the higher-order ones itself:

``pjit`` / ``closed_call`` / ``custom_jvp_call``
    straight sub-interpretation of the inner jaxpr.

``cond``
    join over the feasible branches; a constant-interval branch index
    prunes the rest (dead branches are not analyzed, so a guard like
    ``lax.cond(debug, ...)`` with a literal False never reports).

``while``
    join-to-fixpoint with **condition refinement**: when the cond jaxpr
    is a direct comparison between a carry component and a bound
    (``fori_loop`` lowers to exactly this), the component's interval is
    met with the branch condition at every body entry — that is the
    inductive bound for loop counters, so counter-indexed
    ``dynamic_slice`` starts are *proven* rather than widened away.
    Components still unstable after ``FIXPOINT_PASSES`` are widened
    per-endpoint to their dtype bound, then narrowed back through the
    refinement and re-verified by Park induction
    (``init ⊔ body(refine(C)) ⊆ C``).

``scan``
    the trip count is static, which buys more than ``while``: short
    loops (≤ ``UNROLL_LIMIT``) are unrolled exactly; longer ones run
    join-to-fixpoint, and carry components that keep growing (monotone
    counters — a round number bumped per event) get **length-aware
    extent extrapolation**: per-pass growth ``g`` is measured at the
    current carry, the candidate ``C = base ⊕ L·g`` is probed by
    re-running the body at ``C`` and accepting only if the growth there
    is no worse than ``g`` (translation-style steps; anything else
    falls back to the dtype bound).  This is how the audit proves
    ``rounds ≤ events ≪ 2**31`` instead of widening every counter to
    "might wrap".  A candidate escaping its dtype *is* the overflow
    proof and reports SW008 at the scan site.

``shard_map``
    sub-interpretation of the per-shard jaxpr with the mesh's axis
    sizes pushed into scope, so ``psum`` scales by the real axis extent
    and ``axis_index`` gets ``[0, axis-1]``.

Exploration passes (fixpoint/widening/probes) run *quiet*; once a loop
converges, one loud pass over the final abstract state emits findings.
Findings are deduplicated by (rule, site, primitive), so an unrolled
loop reports each offending site once.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpu_swirld.analysis.lint import Finding
from tpu_swirld.analysis.flow.lattice import (
    AbsVal,
    Interval,
    dtype_range,
    is_int_dtype,
)
from tpu_swirld.analysis.flow.transfer import (
    _FLIP,
    _refine_by_pred,
    HIGHER_ORDER,
    UnknownPrimitiveError,
    apply_transfer,
)

UNROLL_LIMIT = 64
FIXPOINT_PASSES = 12
SETTLE_PASSES = 4

RULE_NAMES = {
    "SW008": "overflow-reachable",
    "SW009": "unproven-bounds",
    "SW010": "lossy-narrowing",
    "SW011": "sentinel-collision",
}


def _src(eqn) -> Tuple[str, int]:
    """Best user-code (file, line) for an eqn from its source_info."""
    frames = []
    try:
        from jax._src import source_info_util

        frames = list(source_info_util.user_frames(eqn.source_info))
    except Exception:
        pass
    best = None
    for fr in frames:
        fn = getattr(fr, "file_name", "") or ""
        posix = fn.replace(os.sep, "/")
        if "tpu_swirld" in posix and "/analysis/" not in posix:
            best = fr
            break
    if best is None and frames:
        best = frames[0]
    if best is None:
        return "<jaxpr>", 0
    line = getattr(best, "start_line", None)
    if not line:
        line = getattr(best, "line_num", 0) or 0
    return best.file_name, int(line)


@dataclasses.dataclass
class FlowResult:
    outs: List[AbsVal]
    findings: List[Finding]
    exercised: set
    env_samples: Dict[str, AbsVal]


class _Analysis:
    """State shared across every (sub-)jaxpr walk of one interpretation."""

    def __init__(self, stage, sentinels, axis_sizes, findings, exercised):
        self.stage = stage
        self.sentinels = tuple(sentinels)
        self.axis_sizes = dict(axis_sizes or {})
        self.findings = findings if findings is not None else []
        self.exercised = exercised if exercised is not None else set()
        self.quiet = 0
        self._seen = set()

    def report(self, rule, eqn, msg):
        if self.quiet:
            return
        path, line = _src(eqn)
        key = (rule, path, line, eqn.primitive.name, msg.split(":")[0])
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(
            Finding(rule, RULE_NAMES.get(rule, rule), path, line, 0,
                    f"[{self.stage}] {msg}")
        )


class _Frame:
    """Per-jaxpr context handed to transfer functions."""

    def __init__(self, an: _Analysis):
        self.an = an
        self.env: Dict = {}
        self.defs: Dict = {}

    # --- interface used by transfer.py -----------------------------------
    @property
    def stage(self):
        return self.an.stage

    @property
    def sentinels(self):
        return self.an.sentinels

    @property
    def axis_sizes(self):
        return self.an.axis_sizes

    @property
    def exercised(self):
        return self.an.exercised

    def report(self, rule, eqn, msg):
        self.an.report(rule, eqn, msg)

    def where(self, eqn):
        path, line = _src(eqn)
        return f"{path}:{line}"

    def read(self, atom) -> AbsVal:
        import jax.core as jcore

        if isinstance(atom, jcore.Literal):
            return _literal_absval(atom)
        return self.env[atom]

    def env_lookup(self, atom) -> Optional[AbsVal]:
        import jax.core as jcore

        if isinstance(atom, jcore.Literal):
            return _literal_absval(atom)
        return self.env.get(atom)

    def const_interval(self, atom) -> Optional[Interval]:
        v = self.env_lookup(atom)
        return v.iv if v is not None else None


def _literal_absval(atom) -> AbsVal:
    """AbsVal for a jaxpr Literal, taking shape/dtype from the atom's
    aval (``np.asarray(0)`` would default a Python-int literal to int64
    and break joins against the jaxpr's declared int32)."""
    v = AbsVal.from_literal(atom.val)
    aval = atom.aval
    if hasattr(aval, "dtype"):
        v = dataclasses.replace(
            v, shape=tuple(aval.shape), dtype=np.dtype(aval.dtype))
    return v


def _bind_arg(invar, val: Optional[AbsVal]) -> AbsVal:
    aval = invar.aval
    if not hasattr(aval, "dtype"):
        return AbsVal((), np.dtype(np.int32), Interval(0, 0), True)
    if val is None:
        return AbsVal.from_aval(aval)
    return AbsVal.from_aval(aval, val.iv, val.integral).clamp_to_dtype()


def _eval_closed(an: _Analysis, closed, args: Sequence[AbsVal]):
    consts = []
    for c in closed.consts:
        try:
            consts.append(AbsVal.from_literal(np.asarray(c)))
        except Exception:
            consts.append(AbsVal((), np.dtype(np.int32), Interval(0, 0), True))
    return _eval_jaxpr(an, closed.jaxpr, consts, args)


def _eval_jaxpr(an: _Analysis, jaxpr, consts: Sequence[AbsVal],
                args: Sequence[AbsVal]):
    frame = _Frame(an)
    for v, c in zip(jaxpr.constvars, consts):
        frame.env[v] = c
    for v, a in zip(jaxpr.invars, args):
        frame.env[v] = _bind_arg(v, a)
    for eqn in jaxpr.eqns:
        in_vals = [frame.read(x) for x in eqn.invars]
        name = eqn.primitive.name
        if name in HIGHER_ORDER:
            outs = _eval_higher_order(an, frame, eqn, in_vals)
            an.exercised.add(name)
        else:
            outs = apply_transfer(frame, eqn, in_vals)
        for ov, o in zip(eqn.outvars, outs):
            frame.env[ov] = o
            frame.defs[ov] = eqn
    return [frame.read(x) for x in jaxpr.outvars], frame


# --------------------------------------------------------------------------
# higher-order primitives


def _remainder_summary(a: Interval, b: Interval) -> Optional[Interval]:
    """Closed-form interval of ``jnp.remainder(a, b)`` (floored mod) when
    the divisor interval has a definite sign; None when it spans zero."""
    if a.is_bottom or b.is_bottom:
        return None
    if b.lo > 0:
        if a.lo >= 0 and a.hi < b.lo:
            return a          # already reduced
        return Interval(0, b.hi - 1)
    if b.hi < 0:
        return Interval(b.lo + 1, 0)
    return None


def _eval_higher_order(an, frame, eqn, args):
    name = eqn.primitive.name
    if name in ("pjit", "closed_call", "core_call"):
        inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
        outs, _ = _eval_closed(an, inner, args)
        if (
            eqn.params.get("name") == "remainder"
            and len(args) == 2
            and len(outs) == 1
            and is_int_dtype(outs[0].dtype)
        ):
            # Known-function summary: jnp.remainder is floored mod (result
            # sign follows the divisor).  The sign-fix select inside uses a
            # compound predicate that defeats path refinement, so meet the
            # descended result with the closed form.
            s = _remainder_summary(args[0].iv, args[1].iv)
            if s is not None:
                outs[0] = dataclasses.replace(outs[0], iv=outs[0].iv.meet(s))
        return outs
    if name in ("custom_jvp_call", "custom_vjp_call"):
        inner = eqn.params.get("call_jaxpr") or eqn.params.get("fun_jaxpr")
        outs, _ = _eval_closed(an, inner, args)
        return outs
    if name in ("remat", "checkpoint"):
        inner = eqn.params["jaxpr"]
        outs, _ = _eval_jaxpr(an, inner, [], args)
        return outs
    if name == "cond":
        return _eval_cond(an, eqn, args)
    if name == "while":
        return _eval_while(an, eqn, args)
    if name == "scan":
        return _eval_scan(an, eqn, args)
    if name == "shard_map":
        return _eval_shard_map(an, eqn, args)
    raise UnknownPrimitiveError(name, an.stage, frame.where(eqn))


def _eval_cond(an, eqn, args):
    branches = eqn.params["branches"]
    index, ops = args[0], args[1:]
    lo = 0 if index.iv.is_bottom else max(0, int(index.iv.lo))
    hi = len(branches) - 1 if index.iv.is_bottom else min(
        len(branches) - 1, int(index.iv.hi))
    if lo > hi:
        lo, hi = 0, len(branches) - 1
    outs = None
    for b in branches[lo:hi + 1]:
        b_outs, _ = _eval_closed(an, b, ops)
        if outs is None:
            outs = b_outs
        else:
            outs = [o.join(n) for o, n in zip(outs, b_outs)]
    return outs


def _cond_info(an, cond_closed, cc, carry):
    """Refine carry under "condition is True"; also return the
    ``(carry_index, op, bound_interval)`` constraints found, so the
    while handler can derive a trip-count bound for counters."""
    carry = list(carry)
    constraints = []
    an.quiet += 1
    try:
        try:
            _, fr = _eval_closed(an, cond_closed, list(cc) + carry)
        except UnknownPrimitiveError:
            return carry, constraints
    finally:
        an.quiet -= 1
    jx = cond_closed.jaxpr
    out = jx.outvars[0]
    prod = fr.defs.get(out)
    if prod is None or prod.primitive.name not in _FLIP:
        return carry, constraints
    lhs, rhs = prod.invars
    for var, bound, op in (
        (lhs, rhs, prod.primitive.name),
        (rhs, lhs, _FLIP[prod.primitive.name]),
    ):
        try:
            pos = jx.invars.index(var)
        except (ValueError, TypeError):
            continue
        ci = pos - len(cc)
        if ci < 0 or ci >= len(carry):
            continue
        b_iv = fr.const_interval(bound)
        if b_iv is None or b_iv.is_bottom:
            continue
        refined = _refine_by_pred(carry[ci].iv, op, b_iv, True)
        if not refined.is_bottom:
            carry[ci] = carry[ci].with_iv(refined)
        constraints.append((ci, op, b_iv))
    return carry, constraints


def _cond_refine(an, cond_closed, cc, carry):
    return _cond_info(an, cond_closed, cc, carry)[0]


def _widen_unstable(carry, prev):
    """Per-endpoint widening: any endpoint still moving goes to its
    dtype bound; the stable endpoint is kept."""
    out = []
    for c, p in zip(carry, prev):
        lo_d, hi_d = dtype_range(c.dtype)
        lo = c.iv.lo if c.iv.lo == p.iv.lo else lo_d
        hi = c.iv.hi if c.iv.hi == p.iv.hi else hi_d
        out.append(c.with_iv(Interval(lo, hi)))
    return out


def _literal_step(jx, out_atom, in_var):
    """Constant k when the body computes ``out = in_var + k`` at top
    level (the fori_loop counter pattern); None otherwise."""
    import jax.core as jcore

    if isinstance(out_atom, jcore.Literal):
        return None
    prod = None
    for e in jx.eqns:
        if out_atom in e.outvars:
            prod = e
    if prod is None or prod.primitive.name != "add":
        return None
    a, b = prod.invars
    for x, y in ((a, b), (b, a)):
        if x is in_var and isinstance(y, jcore.Literal):
            try:
                return int(np.asarray(y.val))
            except Exception:
                return None
    return None


def _while_trip_bound(body_closed, nbc, constraints, init):
    """Trip-count bound for a while loop whose condition is
    ``counter < bound`` and whose body bumps the counter by a literal
    ``k >= 1`` — the only pattern where interval data gives a *sound*
    bound (a conditionally-advancing counter would not)."""
    from tpu_swirld.analysis.flow.lattice import NEG_INF, POS_INF

    jx = body_closed.jaxpr
    for ci, op, b_iv in constraints:
        if op not in ("lt", "le") or b_iv.hi == POS_INF:
            continue
        if init[ci].iv.is_bottom or init[ci].iv.lo in (NEG_INF, POS_INF):
            continue
        step = _literal_step(jx, jx.outvars[ci], jx.invars[nbc + ci])
        if step is None or step < 1:
            continue
        span = b_iv.hi - init[ci].iv.lo + (1 if op == "le" else 0)
        return max(0, -(-int(span) // step))
    return None


def _eval_while(an, eqn, args):
    ncc = eqn.params["cond_nconsts"]
    nbc = eqn.params["body_nconsts"]
    cond_jaxpr = eqn.params["cond_jaxpr"]
    body_jaxpr = eqn.params["body_jaxpr"]
    cc = args[:ncc]
    bc = args[ncc:ncc + nbc]
    init = list(args[ncc + nbc:])
    carry = list(init)
    an.quiet += 1
    try:
        prev = carry
        stable = False
        constraints = []
        for _ in range(FIXPOINT_PASSES):
            entry, constraints = _cond_info(an, cond_jaxpr, cc, carry)
            outs, _ = _eval_closed(an, body_jaxpr, list(bc) + entry)
            new = [c.join(o) for c, o in zip(carry, outs)]
            if all(c.covers(n) for c, n in zip(carry, new)):
                stable = True
                break
            prev, carry = carry, new
        if not stable:
            # a ``counter < bound`` condition on a strictly-growing carry
            # component bounds the trip count — extent-extrapolate the
            # other movers like a fixed-length scan.
            trip = _while_trip_bound(body_jaxpr, nbc, constraints, init)
            if trip is not None:
                def run(c):
                    e = _cond_refine(an, cond_jaxpr, cc, c)
                    outs, _ = _eval_closed(an, body_jaxpr, list(bc) + e)
                    return outs, ()

                carry = _extrapolate_scan(
                    an, eqn, run, init, carry, prev, trip)
                stable = True
        if not stable:
            wide = _widen_unstable(carry, prev)
            # narrow back through the refinement; verify by Park induction
            entry = _cond_refine(an, cond_jaxpr, cc, wide)
            outs, _ = _eval_closed(an, body_jaxpr, list(bc) + entry)
            cand = [i.join(e).join(o) for i, e, o in zip(init, entry, outs)]
            ok = False
            for _ in range(SETTLE_PASSES):
                entry = _cond_refine(an, cond_jaxpr, cc, cand)
                outs, _ = _eval_closed(an, body_jaxpr, list(bc) + entry)
                nxt = [i.join(e).join(o)
                       for i, e, o in zip(init, entry, outs)]
                if all(c.covers(n) for c, n in zip(cand, nxt)):
                    ok = True
                    break
                cand = [c.join(n) for c, n in zip(cand, nxt)]
            carry = cand if ok else wide
    finally:
        an.quiet -= 1
    # loud pass over the converged state (cond + body findings)
    entry = _cond_refine(an, cond_jaxpr, cc, carry)
    _eval_closed(an, cond_jaxpr, list(cc) + carry)
    outs, _ = _eval_closed(an, body_jaxpr, list(bc) + entry)
    return [c.join(o) for c, o in zip(carry, outs)]


def _eval_scan(an, eqn, args):
    p = eqn.params
    body = p["jaxpr"]
    length = int(p["length"])
    n_consts = p["num_consts"]
    n_carry = p["num_carry"]
    consts = args[:n_consts]
    init = list(args[n_consts:n_consts + n_carry])
    xs = args[n_consts + n_carry:]
    x_slices = [AbsVal(x.shape[1:] if x.shape else (), x.dtype, x.iv,
                       x.integral) for x in xs]

    def run(carry):
        outs, _ = _eval_closed(an, body, list(consts) + list(carry)
                               + list(x_slices))
        return outs[:n_carry], outs[n_carry:]

    if length <= UNROLL_LIMIT:
        carry = init
        ys = None
        for _ in range(max(length, 1)):
            carry, y = run(carry)
            ys = y if ys is None else [a.join(b) for a, b in zip(ys, y)]
        return _scan_outs(eqn, n_carry, carry, ys)

    an.quiet += 1
    try:
        carry, prev = list(init), list(init)
        stable = False
        for _ in range(FIXPOINT_PASSES):
            outs, _ = run(carry)
            new = [c.join(o) for c, o in zip(carry, outs)]
            if all(c.covers(n) for c, n in zip(carry, new)):
                stable = True
                break
            prev, carry = carry, new
        if not stable:
            carry = _extrapolate_scan(an, eqn, run, init, carry, prev, length)
    finally:
        an.quiet -= 1
    outs, ys = run(carry)  # loud final pass
    carry = [c.join(o) for c, o in zip(carry, outs)]
    return _scan_outs(eqn, n_carry, carry, ys)


def _scan_outs(eqn, n_carry, carry, ys):
    out_vals = list(carry)
    for j, y in enumerate(ys or []):
        ov = eqn.outvars[n_carry + j]
        out_vals.append(AbsVal.from_aval(ov.aval, y.iv, y.integral))
    return out_vals


def _extrapolate_scan(an, eqn, run, init, carry, prev, length):
    """Length-aware extent extrapolation for monotone scan carries.

    Growth per pass ``g`` is measured between the last two joined
    carries; the candidate ``C = carry ⊕ length·g`` is accepted for a
    component only if re-running the body *at C* grows no faster than
    ``g`` (translation-style step).  A candidate past the dtype range is
    a proven overflow: SW008 at the scan site, then clamp.  Components
    that fail the probe widen to their dtype bound.
    """
    grow = []
    for c, pr in zip(carry, prev):
        g_lo = min(0, c.iv.lo - pr.iv.lo)
        g_hi = max(0, c.iv.hi - pr.iv.hi)
        grow.append((g_lo, g_hi))
    # The body of iteration k sees the carry *input*, i.e. at most
    # init + (length-1)·g for a translation-style step — basing the
    # candidate on the fixpoint-observed carry would overshoot by the
    # passes already run (a counter would read [0, length+passes] and
    # fail its own in-bounds gather at exactly the envelope extent).
    ext = max(length - 1, 0)
    cand = []
    for i, (c, (g_lo, g_hi)) in enumerate(zip(carry, grow)):
        if g_lo == 0 and g_hi == 0:
            cand.append(c)
            continue
        ini = init[i]
        base = ini if not ini.iv.is_bottom else c
        cand.append(c.join(c.with_iv(Interval(base.iv.lo + ext * g_lo,
                                              base.iv.hi + ext * g_hi))))
    probe, _ = run(cand)
    final = []
    frozen = []
    for i, (c, cd, (g_lo, g_hi), pb) in enumerate(
            zip(carry, cand, grow, probe)):
        if g_lo == 0 and g_hi == 0:
            # stable component: keep, folding in any probe drift
            final.append(c if c.covers(pb) else c.join(pb))
            frozen.append(False)
            continue
        ok = (pb.iv.lo >= cd.iv.lo + g_lo - abs(g_lo)
              and pb.iv.hi <= cd.iv.hi + g_hi + abs(g_hi))
        v = cd if ok else cd.top_like()
        if is_int_dtype(v.dtype):
            lo_d, hi_d = dtype_range(v.dtype)
            if v.iv.lo < lo_d or v.iv.hi > hi_d:
                an.report(
                    "SW008", eqn,
                    f"scan: carry component {i} grows ~[{g_lo}, {g_hi}] per "
                    f"step over {length} steps, reaching {v.iv} — outside "
                    f"{np.dtype(v.dtype).name} range [{lo_d}, {hi_d}]",
                )
                v = v.clamp_to_dtype()
                ok = False
        final.append(v)
        # A translation-verified component's in-body *input* never exceeds
        # init + (length-1)·g; joining its own +g output back in while
        # settling the others would inflate a loop counter past the trip
        # count (and fail in-bounds gathers at exactly the extent).
        frozen.append(ok)
    # settle the rest against the extrapolated components
    carry = final
    new = carry
    for _ in range(SETTLE_PASSES):
        outs, _ = run(carry)
        # re-verify frozen components against the (possibly widened)
        # rest; a faster-growing step voids the translation argument
        for i, (g_lo, g_hi) in enumerate(grow):
            if frozen[i] and not (
                outs[i].iv.lo >= carry[i].iv.lo + g_lo - abs(g_lo)
                and outs[i].iv.hi <= carry[i].iv.hi + g_hi + abs(g_hi)
            ):
                frozen[i] = False
        new = [c if fz else c.join(o)
               for c, o, fz in zip(carry, outs, frozen)]
        if all(fz or c.covers(n)
               for c, n, fz in zip(carry, new, frozen)):
            return new
        carry = new
    # still moving: dtype-bound the movers and finish
    return [c if fz else (c.top_like() if not c.covers(n) else c)
            for c, n, fz in zip(carry, new, frozen)]


def _eval_shard_map(an, eqn, args):
    mesh = eqn.params.get("mesh")
    inner = eqn.params.get("jaxpr")
    saved = dict(an.axis_sizes)
    try:
        if mesh is not None:
            # caller-declared axis sizes (the envelope's mesh_devices) win
            # over the traced mesh — the audit traces shard_map under
            # whatever mesh the host has (often 1 CPU device) while
            # proving the envelope's device count.
            try:
                for k, v in dict(mesh.shape).items():
                    an.axis_sizes.setdefault(str(k), int(v))
            except Exception:
                pass
        if hasattr(inner, "jaxpr"):  # ClosedJaxpr
            outs, _ = _eval_closed(an, inner, args)
        else:
            outs, _ = _eval_jaxpr(an, inner, [], args)
        # shard_map outvars carry the *global* shape; rebuild on out avals
        return [AbsVal.from_aval(ov.aval, o.iv, o.integral)
                for ov, o in zip(eqn.outvars, outs)]
    finally:
        an.axis_sizes = saved


# --------------------------------------------------------------------------
# entry point


def interpret_jaxpr(
    closed,
    arg_vals: Optional[Sequence] = None,
    *,
    stage: str = "<fn>",
    sentinels: Sequence[int] = (),
    axis_sizes: Optional[Dict[str, int]] = None,
    findings: Optional[List[Finding]] = None,
    exercised: Optional[set] = None,
) -> FlowResult:
    """Interpret a ``ClosedJaxpr`` abstractly.

    ``arg_vals`` aligns with the jaxpr invars; each entry is an
    :class:`AbsVal`, an :class:`Interval`, a ``(lo, hi)`` tuple, or
    ``None`` (= full dtype range).  Returns the abstract outputs plus
    all findings and the set of primitive names exercised.
    """
    an = _Analysis(stage, sentinels, axis_sizes, findings, exercised)
    invars = closed.jaxpr.invars
    vals: List[Optional[AbsVal]] = []
    for i, v in enumerate(invars):
        raw = arg_vals[i] if arg_vals is not None and i < len(arg_vals) else None
        if raw is None:
            vals.append(None)
        elif isinstance(raw, AbsVal):
            vals.append(raw)
        elif isinstance(raw, Interval):
            vals.append(AbsVal.from_aval(v.aval, raw))
        else:
            lo, hi = raw
            vals.append(AbsVal.from_aval(v.aval, Interval(lo, hi)))
    outs, frame = _eval_closed(an, closed, vals)
    samples = {}
    return FlowResult(outs=outs, findings=an.findings,
                      exercised=an.exercised, env_samples=samples)
