"""Stage catalog: every jitted consensus stage traced at envelope shapes.

Each :class:`StageSpec` names one jit boundary of the consensus core (the
``obs.stage_call`` name the drivers dispatch it under), and knows how to
build its ``jax.make_jaxpr`` trace at a :class:`~tpu_swirld.analysis.flow.
envelope.ScaleEnvelope`'s shapes together with the *declared input
intervals* — the driver-guaranteed value bounds the abstract interpreter
starts from:

======================  ====================================================
input                   declared interval (driver invariant)
======================  ====================================================
``parents``             ``[-1, N-1]`` — packed parent ids, -1 = genesis
``creator``             ``[0, M-1]`` — packer-validated member index
``stake``               ``[0, stake_max]`` — config-declared per-member cap
``member_table``        ``[-1, N-1]`` — -1 pads unused fork-tip slots
``fork_pairs``          ``[-1, N-1]`` — padded accusation rows
``coin``                ``[0, 1]`` — signature coin *bit* (uint8)
``t_rank``              ``[0, N-1]`` — dense rank of the int64 timestamps
``wit_table``           ``[-1, N-1]`` — -1 = empty witness slot
``wit_count``           ``[0, s_cap]``
``famous``              ``[-1, 1]`` — int8 tri-state
``col_pos`` / ``cols``  ``[-1, C-1]`` / ``[-1, N-1]`` — -1 = no column
``row0`` / ``start``    in-range block starts (``[0, N-rows]`` etc.)
``rnd`` / ``max_round`` ``[0, N-1]`` — a round index never exceeds the
                        event count (each round needs a fresh witness)
======================  ====================================================

Window-engine specs use the window extent ``W = env.rows`` in place of
``N`` for window-local ids (the drivers remap parents/witnesses into the
resident window before dispatch) while *round numbers stay absolute*
(bounded by ``N``).

The catalog is keyed twice: by unique ``spec_id`` for the audit report,
and by ``stage_name`` for the engine-coverage check — a small observed
run of each engine (:func:`observed_stage_names`, the same
``obs.set_stage_observer`` seam as ``jit_audit.runtime_audit``) must find
every dispatched stage name covered by at least one spec, so a new jit
boundary cannot silently escape the audit.

Mesh specs trace ``shard_map`` under whatever mesh the host can build
(often a single CPU device) while the interpreter scales collectives by
the envelope's ``mesh_devices`` — a sound over-approximation of any
smaller real mesh.

Matmul dtype note: specs trace the ``float32`` hop path.  The bfloat16
hop casts the same 0/1 operands (exact in bf16) and accumulates in f32
(``preferred_element_type``), so its value ranges are identical; the
dtype name only selects the cast.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpu_swirld.analysis.flow.envelope import ScaleEnvelope

_BOOL = np.dtype(bool)
_I32 = np.dtype(np.int32)
_I8 = np.dtype(np.int8)
_U8 = np.dtype(np.uint8)

_F32 = "float32"


@dataclasses.dataclass(frozen=True)
class ArgDecl:
    """One traced stage argument: shape, dtype, declared value interval
    (``None`` = full dtype range)."""

    shape: Tuple[int, ...]
    dtype: object
    lo: Optional[int] = None
    hi: Optional[int] = None

    def struct(self):
        import jax

        return jax.ShapeDtypeStruct(self.shape, self.dtype)

    @property
    def iv(self):
        return None if self.lo is None else (self.lo, self.hi)


def _arr(shape, dtype=_I32, lo=None, hi=None):
    return ArgDecl(tuple(shape), dtype, lo, hi)


def _mask(shape):
    return ArgDecl(tuple(shape), _BOOL, 0, 1)


def _scalar(lo, hi, dtype=_I32):
    return ArgDecl((), dtype, lo, hi)


@dataclasses.dataclass(frozen=True)
class StageSpec:
    """One auditable jit boundary."""

    spec_id: str                 # unique catalog key ("batch.rounds_chunk")
    stage_name: str              # obs.stage_call name this trace covers
    engines: Tuple[str, ...]     # engines that dispatch it
    build: Callable              # env -> (fn, static_kwargs, [ArgDecl])


def trace_spec(spec: StageSpec, env: ScaleEnvelope):
    """``(closed_jaxpr, arg_intervals)`` for one spec at envelope shapes."""
    fn, statics, decls = spec.build(env)
    f = functools.partial(fn, **statics) if statics else fn
    import jax

    closed = jax.make_jaxpr(f)(*[d.struct() for d in decls])
    return closed, [d.iv for d in decls]


# --------------------------------------------------------------------------
# shared shape/interval vocabulary


def _dims(env: ScaleEnvelope):
    """Envelope dimensions as used by the specs (window extents never
    exceed the event count)."""
    N = env.events
    W = min(env.rows, N)
    C = min(env.wcols, N)
    return dict(
        N=N, W=W, C=C,
        M=env.members, K=env.k_cap, G=env.fork_groups,
        R=env.r_cap, S=env.s_cap,
        block=min(env.block, W), chunk=min(env.chunk, W),
        chain=env.chain_cap,
        tot=env.tot_stake, smax=env.stake_max,
    )


# --------------------------------------------------------------------------
# batch engine (full-N shapes)


def _b_visibility(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    N, M, G = d["N"], d["M"], d["G"]
    return (
        P.visibility_stage,
        dict(n_members=M, block=d["block"], matmul_dtype_name=_F32),
        [
            _arr((N, 2), _I32, -1, N - 1),       # parents
            _arr((N,), _I32, 0, M - 1),          # creator
            _arr((G, 3), _I32, -1, N - 1),       # fork_pairs
        ],
    )


def _b_ancestry(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    N = d["N"]
    return (
        P.ancestry_stage,
        dict(block=d["block"], matmul_dtype_name=_F32),
        [_arr((N, 2), _I32, -1, N - 1)],
    )


def _b_ssm_gather_rows(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    N, M, K = d["N"], d["M"], d["K"]
    return (
        P.ssm_gather_rows_stage,
        dict(rows=N),
        [
            _mask((N, N)),                        # sees
            _arr((M, K), _I32, -1, N - 1),        # member_table
            _scalar(0, 0),                        # row0 (batch gathers all)
        ],
    )


def _ssm_block_decls(n, rows, cb, m, k):
    return [
        _mask((n, n)),                            # sees
        _arr((m, k), _I32, -1, n - 1),            # member_table
        _arr((m,), _I32, 0, None),                # stake (hi filled later)
        _arr((cb,), _I32, -1, n - 1),             # cols
        _scalar(0, n - rows),                     # row0
    ]


def _b_ssm_block(env, k):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    N, M, C = d["N"], d["M"], d["C"]
    rows = max(256, N // 2)
    decls = _ssm_block_decls(N, rows, C, M, k)
    decls[2] = _arr((M,), _I32, 0, d["smax"])
    return (
        P.ssm_block_stage,
        dict(rows=rows, tot_stake=d["tot"], matmul_dtype_name=_F32),
        decls,
    )


def _b_ssm_block_from_rows(env, k):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    N, M, C = d["N"], d["M"], d["C"]
    rows = max(256, N // 2)
    return (
        P.ssm_block_from_rows_stage,
        dict(rows=rows, tot_stake=d["tot"], matmul_dtype_name=_F32),
        [
            _mask((M, N, k)),                     # a_r3 (gathered a-side)
            _mask((N, N)),                        # sees
            _arr((M, k), _I32, -1, N - 1),        # member_table
            _arr((M,), _I32, 0, d["smax"]),       # stake
            _arr((C,), _I32, -1, N - 1),          # cols
            _scalar(0, N - rows),                 # row_off
        ],
    )


def _rounds_chunk_decls(n, c, m, r, s, chunk, r_hi):
    return [
        _arr((n, 2), _I32, -1, n - 1),            # parents
        _mask((n, c)),                            # ssm_c
        _arr((n,), _I32, -1, c - 1),              # col_pos
        _arr((n,), _I32, 0, m - 1),               # creator
        None,                                     # stake — filled by caller
        _scalar(0, n),                            # n_valid
        _arr((n,), _I32, 0, r_hi),                # rnd (absolute rounds)
        _mask((n,)),                              # wits
        _arr((r, s), _I32, -1, n - 1),            # tab
        _arr((r,), _I32, 0, s),                   # cnt
        _scalar(0, 3),                            # overflow bits
        _scalar(0, max(n - chunk, 0)),            # start
        _scalar(0, r_hi),                         # r_base
    ]


def _b_rounds_chunk(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    N, C, M, R, S = d["N"], d["C"], d["M"], d["R"], d["S"]
    decls = _rounds_chunk_decls(N, C, M, R, S, d["chunk"], N - 1)
    decls[4] = _arr((M,), _I32, 0, d["smax"])
    return (
        P.rounds_chunk_stage,
        dict(tot_stake=d["tot"], r_max=R, s_max=S, has_forks=True,
             chunk=d["chunk"]),
        decls,
    )


def _b_fame_order_cols(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    N, C, M, R, S = d["N"], d["C"], d["M"], d["R"], d["S"]
    return (
        P.fame_order_cols_stage,
        dict(tot_stake=d["tot"], coin_period=env.coin_period, r_max=R,
             s_max=S, chain=d["chain"], has_forks=True,
             matmul_dtype_name=_F32),
        [
            _mask((N, N)),                        # anc
            _mask((N, N)),                        # sees
            _mask((N, C)),                        # ssm_c
            _arr((N,), _I32, -1, C - 1),          # col_pos
            _arr((R, S), _I32, -1, N - 1),        # wit_table
            _arr((R,), _I32, 0, S),               # wit_count
            _arr((N,), _I32, 0, M - 1),           # creator
            _arr((N,), _U8, 0, 1),                # coin
            _arr((M,), _I32, 0, d["smax"]),       # stake
            _arr((N,), _I32, -1, N - 1),          # self_parent
            _arr((N,), _I32, 0, N - 1),           # t_rank
            _scalar(0, N - 1),                    # max_round
            _scalar(0, N),                        # n_valid
        ],
    )


def _b_rounds_stage(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    N, M, G = d["N"], d["M"], d["G"]
    return (
        P.rounds_stage,
        dict(tot_stake=d["tot"], block=d["block"], r_max=d["R"],
             s_max=d["S"], has_forks=True, matmul_dtype_name=_F32),
        [
            _arr((N, 2), _I32, -1, N - 1),        # parents
            _arr((N,), _I32, 0, M - 1),           # creator
            _arr((M,), _I32, 0, d["smax"]),       # stake
            _arr((G, 3), _I32, -1, N - 1),        # fork_pairs
            _arr((M, d["K"]), _I32, -1, N - 1),   # member_table
            _scalar(0, N),                        # n_valid
        ],
    )


def _b_fame_order_stage(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    N, M, R, S = d["N"], d["M"], d["R"], d["S"]
    return (
        P.fame_order_stage,
        dict(tot_stake=d["tot"], coin_period=env.coin_period, r_max=R,
             s_max=S, chain=d["chain"], has_forks=True,
             matmul_dtype_name=_F32),
        [
            _mask((N, N)),                        # anc
            _mask((N, N)),                        # sees
            _mask((N, N)),                        # ssm (full matrix path)
            _arr((R, S), _I32, -1, N - 1),        # wit_table
            _arr((R,), _I32, 0, S),               # wit_count
            _arr((N,), _I32, 0, M - 1),           # creator
            _arr((N,), _U8, 0, 1),                # coin
            _arr((M,), _I32, 0, d["smax"]),       # stake
            _arr((N,), _I32, -1, N - 1),          # self_parent
            _arr((N,), _I32, 0, N - 1),           # t_rank
            _scalar(0, N - 1),                    # max_round
            _scalar(0, N),                        # n_valid
        ],
    )


# --------------------------------------------------------------------------
# incremental / streaming engines (window shapes)


def _i_extend_vis(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    W = d["W"]
    nb = W // d["block"]
    return (
        P.make_extend_visibility_stage(P.XLA_EXTENSION_KERNELS),
        dict(block=d["block"], matmul_dtype_name=_F32),
        [
            _mask((W, W)),                        # anc (donated)
            _arr((W, 2), _I32, -1, W - 1),        # parents (window-remapped)
            _scalar(0, nb),                       # b0
            _scalar(0, nb),                       # b1
        ],
    )


def _i_extend_vis_forked(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    W, G, M = d["W"], d["G"], d["M"]
    rows = max(256, W // 2)
    nb = W // d["block"]
    return (
        P.make_extend_visibility_forked_stage(P.XLA_EXTENSION_KERNELS),
        dict(block=d["block"], rows=rows, n_members=M,
             matmul_dtype_name=_F32),
        [
            _mask((W, W)),                        # anc
            _mask((W, W)),                        # sees
            _arr((W, 2), _I32, -1, W - 1),        # parents
            _arr((G, 3), _I32, -1, W - 1),        # fork_pairs (remapped)
            _arr((W,), _I32, 0, M - 1),           # creator
            _scalar(0, nb),                       # b0
            _scalar(0, nb),                       # b1
            _scalar(0, W - rows),                 # row0
        ],
    )


def _i_sees_materialize(env):
    from tpu_swirld.tpu import pipeline as P

    W = _dims(env)["W"]
    return P._copy_slab_stage, {}, [_mask((W, W))]


def _i_ssm_gather_rows(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    W, M, K = d["W"], d["M"], d["K"]
    rows = max(256, W // 2)
    return (
        P.ssm_gather_rows_stage,
        dict(rows=rows),
        [
            _mask((W, W)),
            _arr((M, K), _I32, -1, W - 1),
            _scalar(0, W - rows),
        ],
    )


def _i_ssm_block(env, k):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    W, M, C = d["W"], d["M"], d["C"]
    rows = max(256, W // 2)
    decls = _ssm_block_decls(W, rows, C, M, k)
    decls[2] = _arr((M,), _I32, 0, d["smax"])
    return (
        P.ssm_block_stage,
        dict(rows=rows, tot_stake=d["tot"], matmul_dtype_name=_F32),
        decls,
    )


def _i_ssm_block_from_rows(env, k):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    W, M, C = d["W"], d["M"], d["C"]
    rows = max(256, W // 2)
    return (
        P.ssm_block_from_rows_stage,
        dict(rows=rows, tot_stake=d["tot"], matmul_dtype_name=_F32),
        [
            _mask((M, W, k)),                     # a_r3
            _mask((W, W)),                        # sees
            _arr((M, k), _I32, -1, W - 1),        # member_table
            _arr((M,), _I32, 0, d["smax"]),       # stake
            _arr((C,), _I32, -1, W - 1),          # cols
            _scalar(0, W - rows),                 # row_off
        ],
    )


def _i_ssm_update(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    W, C = d["W"], d["C"]
    rows, cb = max(256, W // 2), min(256, C)
    return (
        P.update_block_stage,
        {},
        [
            _mask((W, C)),                        # ssm_c (donated)
            _mask((rows, cb)),                    # part
            _scalar(0, W - rows),                 # row0
            _scalar(0, C - cb),                   # col0
        ],
    )


def _i_rounds_chunk(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    W, C, M, R, S, N = d["W"], d["C"], d["M"], d["R"], d["S"], d["N"]
    decls = _rounds_chunk_decls(W, C, M, R, S, d["chunk"], N - 1)
    decls[4] = _arr((M,), _I32, 0, d["smax"])
    return (
        P.rounds_chunk_stage,
        dict(tot_stake=d["tot"], r_max=R, s_max=S, has_forks=True,
             chunk=d["chunk"]),
        decls,
    )


def _i_rounds_span(env):
    """Fused K-chunk rounds megadispatch: same carry/decl contract as
    ``rounds_chunk_stage`` but the scan covers ``chunk * k_chunks``
    events per dispatch, so the start scalar's bound tightens to
    ``W - chunk*k`` (the driver only launches spans that fit the
    window) and the interval proof must hold over the widest fused
    trip count the default config can issue (k = 8)."""
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    W, C, M, R, S, N = d["W"], d["C"], d["M"], d["R"], d["S"], d["N"]
    k_chunks = min(8, max(1, W // d["chunk"]))
    decls = _rounds_chunk_decls(
        W, C, M, R, S, d["chunk"] * k_chunks, N - 1
    )
    decls[4] = _arr((M,), _I32, 0, d["smax"])
    return (
        P.rounds_span_stage,
        dict(tot_stake=d["tot"], r_max=R, s_max=S, has_forks=True,
             chunk=d["chunk"], k_chunks=k_chunks),
        decls,
    )


def _i_fame(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    W, C, M, R, S = d["W"], d["C"], d["M"], d["R"], d["S"]
    return (
        P.fame_window_stage,
        dict(tot_stake=d["tot"], coin_period=env.coin_period, r_max=R,
             s_max=S, has_forks=True, matmul_dtype_name=_F32),
        [
            _mask((W, W)),                        # sees
            _mask((W, C)),                        # ssm_c
            _arr((W,), _I32, -1, C - 1),          # col_pos
            _arr((R, S), _I32, -1, W - 1),        # wit_table
            _arr((W,), _I32, 0, M - 1),           # creator
            _arr((W,), _U8, 0, 1),                # coin
            _arr((M,), _I32, 0, d["smax"]),       # stake
        ],
    )


def _i_order(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    W, M, R, S, N = d["W"], d["M"], d["R"], d["S"], d["N"]
    return (
        P.order_window_stage,
        dict(r_max=R, s_max=S, chain=d["chain"]),
        [
            _mask((W, W)),                        # anc
            _arr((R, S), _I32, -1, W - 1),        # wit_table
            _arr((R,), _I32, 0, S),               # wit_count
            _arr((R * S,), _I8, -1, 1),           # famous
            _arr((W,), _I32, 0, M - 1),           # creator
            _arr((W,), _I32, -1, W - 1),          # self_parent
            _arr((W,), _I32, 0, N - 1),           # t_rank
            _scalar(0, R),                        # max_round_local
            _scalar(0, W),                        # n_valid
            _mask((W,)),                          # received0
        ],
    )


def _i_compact_cols(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    W, C = d["W"], d["C"]
    return (
        P.compact_cols_stage,
        {},
        [_mask((W, C)), _arr((C,), _I32, -1, C - 1)],
    )


def _i_prune(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    W, C = d["W"], d["C"]
    return (
        P.prune_stage,
        {},
        [
            _mask((W, W)),                        # anc
            _mask((W, W)),                        # sees
            _mask((W, C)),                        # ssm_c
            _scalar(0, W),                        # d (pruned count)
            _scalar(0, W),                        # n_used
            _arr((C,), _I32, -1, C - 1),          # keep_cols
        ],
    )


def _i_prune_noforks(env):
    from tpu_swirld.tpu import pipeline as P

    d = _dims(env)
    W, C = d["W"], d["C"]
    return (
        P.prune_noforks_stage,
        {},
        [
            _mask((W, W)),
            _mask((W, C)),
            _scalar(0, W),
            _scalar(0, W),
            _arr((C,), _I32, -1, C - 1),
        ],
    )


# --------------------------------------------------------------------------
# mesh engine (shard_map kernels; traced under the host's mesh, collectives
# scaled by the envelope's mesh_devices via interpret's axis_sizes)


def _mesh(env):
    import jax

    from tpu_swirld.parallel import make_mesh

    return make_mesh(min(env.mesh_devices, len(jax.devices())))


def mesh_axis_sizes(env: ScaleEnvelope) -> Dict[str, int]:
    from tpu_swirld.parallel import MEMBER_AXIS

    return {MEMBER_AXIS: env.mesh_devices}


def _m_ssm_block_row(env):
    from tpu_swirld.parallel import make_row_sharded_block_fn

    d = _dims(env)
    W, M, C = d["W"], d["M"], d["C"]
    mesh = _mesh(env)
    dev = int(mesh.devices.size)
    w = (W // dev) * dev or dev           # rows must split evenly
    rows = max(256, w // 2)
    decls = _ssm_block_decls(w, rows, C, M, d["K"])
    decls[2] = _arr((M,), _I32, 0, d["smax"])
    return (
        make_row_sharded_block_fn(mesh),
        dict(rows=rows, tot_stake=d["tot"], matmul_dtype_name=_F32),
        decls,
    )


def _m_ssm_block_member(env):
    from tpu_swirld.parallel import make_ssm_block_fn_for_mesh

    d = _dims(env)
    W, M, C = d["W"], d["M"], d["C"]
    mesh = _mesh(env)
    rows = max(256, W // 2)
    decls = _ssm_block_decls(W, rows, C, M, d["K"])
    decls[2] = _arr((M,), _I32, 0, d["smax"])
    return (
        make_ssm_block_fn_for_mesh(mesh),
        dict(rows=rows, tot_stake=d["tot"], matmul_dtype_name=_F32),
        decls,
    )


def _m_consensus(env):
    from tpu_swirld.parallel import consensus_fn_for_mesh

    d = _dims(env)
    N, M, G = d["N"], d["M"], d["G"]
    mesh = _mesh(env)
    dev = int(mesh.devices.size)
    m = ((M + dev - 1) // dev) * dev      # pad_members contract
    return (
        consensus_fn_for_mesh(mesh),
        dict(tot_stake=d["tot"], coin_period=env.coin_period,
             block=d["block"], r_max=d["R"], s_max=d["S"],
             chain=d["chain"], has_forks=True, matmul_dtype_name=_F32),
        [
            _arr((N, 2), _I32, -1, N - 1),        # parents
            _arr((N,), _I32, 0, M - 1),           # creator
            _arr((N,), _I32, 0, N - 1),           # t_rank
            _arr((N,), _U8, 0, 1),                # coin
            _arr((m,), _I32, 0, d["smax"]),       # stake (padded)
            _arr((G, 3), _I32, -1, N - 1),        # fork_pairs
            _arr((m, d["K"]), _I32, -1, N - 1),   # member_table (padded)
            _scalar(0, N),                        # n_valid
        ],
    )


# --------------------------------------------------------------------------
# dynamic membership (epoch-boundary member-axis repack)


def _mb_repack(env):
    """The :func:`tpu_swirld.membership.repack.repack_stage` boundary at
    its worst case: one joiner extends the member axis M -> M+1, and the
    member table is as tall as a single creator could make it (K = N —
    one member authored every event).  Values are packed event indices
    (``-1`` padding), so the claim is they stay inside int32 at the
    envelope's event count; stake rides the config-declared cap."""
    from tpu_swirld.membership import repack as MR

    d = _dims(env)
    N, M = d["N"], d["M"]
    return (
        MR.repack_stage,
        dict(n_members_new=M + 1),
        [
            _arr((M, N), _I32, -1, N - 1),       # member_table
            _arr((M + 1,), _I32, 0, d["smax"]),  # stake_new
        ],
    )


# --------------------------------------------------------------------------
# catalog


_INC = ("incremental", "streaming", "mesh")

CATALOG: List[StageSpec] = [
    # batch
    StageSpec("batch.visibility", "pipeline.visibility_stage",
              ("batch",), _b_visibility),
    StageSpec("batch.ancestry", "pipeline.visibility_stage",
              ("batch",), _b_ancestry),
    StageSpec("batch.ssm_gather_rows", "pipeline.ssm_gather_rows",
              ("batch",), _b_ssm_gather_rows),
    StageSpec("batch.ssm_block", "pipeline.ssm_block_stage",
              ("batch",), functools.partial(_b_ssm_block, k=8)),
    StageSpec("batch.ssm_block_gemm", "pipeline.ssm_block_stage",
              ("batch",), functools.partial(_b_ssm_block, k=1)),
    StageSpec("batch.ssm_block_from_rows", "pipeline.ssm_block_from_rows",
              ("batch",), functools.partial(_b_ssm_block_from_rows, k=8)),
    StageSpec("batch.ssm_block_from_rows_gemm",
              "pipeline.ssm_block_from_rows",
              ("batch",), functools.partial(_b_ssm_block_from_rows, k=1)),
    StageSpec("batch.rounds_chunk", "pipeline.rounds_chunk_stage",
              ("batch",), _b_rounds_chunk),
    StageSpec("batch.fame_order_cols", "pipeline.fame_order_cols_stage",
              ("batch",), _b_fame_order_cols),
    StageSpec("batch.rounds", "pipeline.rounds_stage",
              ("batch",), _b_rounds_stage),
    StageSpec("batch.fame_order", "pipeline.fame_order_stage",
              ("batch",), _b_fame_order_stage),
    # incremental / streaming windows
    StageSpec("inc.extend_vis", "pipeline.inc_extend_vis",
              _INC, _i_extend_vis),
    StageSpec("inc.extend_vis_forked", "pipeline.inc_extend_vis",
              _INC, _i_extend_vis_forked),
    StageSpec("inc.sees_materialize", "pipeline.sees_materialize",
              _INC, _i_sees_materialize),
    StageSpec("inc.ssm_gather_rows", "pipeline.ssm_gather_rows",
              _INC, _i_ssm_gather_rows),
    StageSpec("inc.ssm_block", "pipeline.ssm_block_stage",
              _INC, functools.partial(_i_ssm_block, k=8)),
    StageSpec("inc.ssm_block_gemm", "pipeline.ssm_block_stage",
              _INC, functools.partial(_i_ssm_block, k=1)),
    StageSpec("inc.ssm_block_from_rows", "pipeline.ssm_block_from_rows",
              _INC, functools.partial(_i_ssm_block_from_rows, k=8)),
    StageSpec("inc.ssm_block_from_rows_gemm",
              "pipeline.ssm_block_from_rows",
              _INC, functools.partial(_i_ssm_block_from_rows, k=1)),
    StageSpec("inc.ssm_update", "pipeline.inc_ssm_update",
              _INC, _i_ssm_update),
    StageSpec("inc.rounds_chunk", "pipeline.rounds_chunk_stage",
              _INC, _i_rounds_chunk),
    StageSpec("inc.rounds_span", "pipeline.rounds_span_stage",
              _INC, _i_rounds_span),
    StageSpec("inc.fame", "pipeline.inc_fame", _INC, _i_fame),
    StageSpec("inc.order", "pipeline.inc_order", _INC, _i_order),
    StageSpec("inc.compact_cols", "pipeline.inc_compact_cols",
              _INC, _i_compact_cols),
    StageSpec("inc.prune", "pipeline.inc_prune", _INC, _i_prune),
    StageSpec("inc.prune_noforks", "pipeline.inc_prune",
              _INC, _i_prune_noforks),
    # dynamic membership: every device engine repacks at epoch
    # boundaries (membership.repack.repack_packer dispatches the stage)
    StageSpec("membership.repack", "membership.repack_stage",
              ("batch",) + _INC, _mb_repack),
    # mesh kernels
    StageSpec("mesh.ssm_block_row", "pipeline.ssm_block_mesh",
              ("mesh",), _m_ssm_block_row),
    StageSpec("mesh.ssm_block_member", "pipeline.ssm_block_stage",
              ("mesh",), _m_ssm_block_member),
    StageSpec("mesh.consensus", "pipeline.mesh_consensus",
              ("batch", "mesh"), _m_consensus),
]

ENGINES = ("batch", "incremental", "streaming", "mesh")


def specs_for_engines(engines: Sequence[str]) -> List[StageSpec]:
    eng = set(engines)
    return [s for s in CATALOG if eng & set(s.engines)]


def coverage_map() -> Dict[str, List[str]]:
    """stage_call name -> spec ids that audit it."""
    out: Dict[str, List[str]] = {}
    for s in CATALOG:
        out.setdefault(s.stage_name, []).append(s.spec_id)
    return out


# --------------------------------------------------------------------------
# engine observation (the jit_audit seam): which stage names does each
# engine actually dispatch?  Every observed name must be in the catalog.


def observed_stage_names(
    engine: str,
    *,
    n_members: int = 6,
    n_events: int = 420,
    seed: int = 3,
    collect: Optional[Callable] = None,
) -> List[str]:
    """Run a small real workload of ``engine`` with the stage observer
    installed and return the sorted stage names it dispatched.

    ``collect(name, fn, args, kw)``, when given, additionally receives
    every observed call (the lattice-soundness property test replays
    them through the interpreter).
    """
    from tpu_swirld import obs as obslib
    from tpu_swirld.config import SwirldConfig
    from tpu_swirld.sim import generate_gossip_dag

    members, stake, events, _ = generate_gossip_dag(
        n_members, n_events, seed=seed, n_forkers=1
    )
    cfg = SwirldConfig(n_members=n_members)
    names: set = set()

    def observer(name, fn, args, kw):
        names.add(name)
        if collect is not None:
            collect(name, fn, args, kw)

    obslib.set_stage_observer(observer)
    try:
        if engine == "batch":
            from tpu_swirld.packing import Packer
            from tpu_swirld.tpu.pipeline import run_consensus

            pk = Packer(members, stake)
            pk.extend(events)
            run_consensus(pk.pack(), cfg, block=64)
        else:
            from tpu_swirld.analysis.jit_audit import runtime_audit as _ra

            if engine == "incremental":
                from tpu_swirld.tpu.pipeline import IncrementalConsensus as D
                drv = D(members, stake, cfg, chunk=64,
                        window_bucket=256, prune_min=64)
            elif engine == "streaming":
                from tpu_swirld.store.streaming import StreamingConsensus as D
                drv = D(members, stake, cfg, chunk=64,
                        window_bucket=256, prune_min=64)
            elif engine == "mesh":
                import jax

                from tpu_swirld.parallel import (
                    MeshStreamingConsensus, make_mesh,
                )
                mesh = make_mesh(min(8, len(jax.devices())))
                drv = MeshStreamingConsensus(
                    mesh, members, stake, cfg, chunk=64,
                    window_bucket=256, prune_min=64,
                )
            else:
                raise ValueError(f"unknown engine {engine!r}")
            _ = _ra  # the seam this mirrors; kept for the cross-reference
            for i in range(0, len(events), 140):
                drv.ingest(events[i:i + 140])
    finally:
        obslib.set_stage_observer(None)
    return sorted(names)


def trace_concrete_call(fn, args, kw):
    """Trace one *observed* stage call: ``(closed_jaxpr, arg_intervals,
    concrete_args)`` with intervals taken from the concrete values — the
    soundness property test's input.  Static (non-array) positional args
    become point intervals."""
    import jax

    structs, ivs = [], []
    for a in args:
        arr = np.asarray(a)
        structs.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))
        if arr.dtype == np.dtype(bool):
            ivs.append(None)
        elif arr.size:
            ivs.append((int(arr.min()), int(arr.max())))
        else:
            ivs.append(None)
    closed = jax.make_jaxpr(functools.partial(fn, **kw))(*structs)
    return closed, ivs
