"""Scale-audit driver: prove every consensus stage safe at an envelope.

``scale_audit`` traces each catalog stage (:mod:`.stages`) to its jaxpr
at the envelope's shapes, runs the abstract interpreter over it, applies
the ``# swirld-lint: disable=SW00x -- <why>`` suppressions from the
flagged source lines (the justification text after ``--`` is
*required*; a bare disable is itself a failure), folds in the host-side
closed-form checks (:func:`~.envelope.host_envelope_findings`), and
verifies stage coverage: every ``obs.stage_call`` name a real small run
of each engine emits must map to at least one audited spec.

Teeth are proven, not assumed: ``--mutate`` re-runs the audit against a
seeded defect (an int16-narrowed tally accumulator, a dropped index
clip) mirroring the real stage code; the auditor must pinpoint it.  The
tier-1 tests assert the exact rule, file, and primitive for each
mutation, so a silently weakened transfer function fails CI.

Exit codes (``python -m tpu_swirld.analysis scale-audit``):

* ``0`` — proven clean at the envelope (all findings suppressed with
  justification, no coverage gaps),
* ``1`` — findings, unjustified suppressions, or coverage gaps,
* ``2`` — the transfer registry met a primitive it does not model (it
  refuses to guess).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tpu_swirld.analysis.lint import Finding, suppression_notes
from tpu_swirld.analysis.flow import stages
from tpu_swirld.analysis.flow.envelope import (
    ScaleEnvelope,
    get_envelope,
    host_envelope_findings,
    preset_names,
)
from tpu_swirld.analysis.flow.interpret import RULE_NAMES, interpret_jaxpr
from tpu_swirld.analysis.flow.transfer import UnknownPrimitiveError


# --------------------------------------------------------------------------
# seeded mutations (the auditor's self-test)


def _mut_ssm_acc_int16(env: ScaleEnvelope):
    """pipeline.ssm_block_stage's member tally with the accumulator
    seeded to int16: the per-member vote sum reaches events*stake_max,
    so the narrowing cast must be flagged (SW010) and the int16
    accumulation wraps (SW008)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    d = stages._dims(env)
    n, b, m = d["N"], d["block"], d["M"]

    @jax.jit
    def mut_ssm_block_tally(sees, creator, stake):
        def body(mm, acc):
            contrib = sees & (creator[None, :] == mm)
            votes = jnp.sum(contrib * stake[mm], axis=1)
            return acc + votes.astype(jnp.int16)  # seeded defect
        acc0 = jnp.zeros((b,), jnp.int16)
        return lax.fori_loop(0, m, body, acc0)

    decls = [
        stages._mask((b, n)),
        stages._arr((n,), lo=0, hi=m - 1),
        stages._arr((m,), lo=0, hi=env.stake_max),
    ]
    return mut_ssm_block_tally, {}, decls


def _mut_dropped_clip(env: ScaleEnvelope):
    """pipeline's rounds-step witness-table lookup with the window-row
    clip dropped: the parent round reaches events-1, far past the
    r_cap-row table — the unclipped gather must be flagged (SW009)."""
    import jax
    import jax.numpy as jnp

    d = stages._dims(env)
    n, r, s = d["N"], d["R"], d["S"]

    @jax.jit
    def mut_rounds_widx(rnd, tab, p1):
        r0 = rnd[jnp.maximum(p1, 0)]
        widx = tab[r0]  # seeded defect: no clip to [0, r_cap-1]
        return widx

    decls = [
        stages._arr((n,), lo=0, hi=n - 1),
        stages._arr((r, s), lo=-1, hi=n - 1),
        stages._scalar(-1, n - 1),
    ]
    return mut_rounds_widx, {}, decls


#: mutation name -> (description, build)
MUTATIONS = {
    "ssm-acc-int16": (
        "narrow the ssm block tally accumulator to int16",
        _mut_ssm_acc_int16,
    ),
    "dropped-clip": (
        "drop the round-window clip before the witness-table gather",
        _mut_dropped_clip,
    ),
}


# --------------------------------------------------------------------------
# report


@dataclasses.dataclass
class AuditReport:
    """Everything one ``scale_audit`` run established."""

    envelope: str
    engines: Tuple[str, ...]
    findings: List[Finding]                    # unsuppressed
    suppressed: List[Tuple[Finding, str]]      # (finding, justification)
    unjustified: List[Finding]                 # bare disables — still fail
    errors: List[str]                          # unknown-primitive reports
    coverage_gaps: Dict[str, List[str]]        # engine -> unaudited stages
    specs: List[str]
    exercised: Set[str]
    mutation: Optional[str] = None

    @property
    def clean(self) -> bool:
        return not (
            self.findings
            or self.unjustified
            or self.errors
            or any(self.coverage_gaps.values())
        )

    @property
    def exit_code(self) -> int:
        if self.errors:
            return 2
        return 0 if self.clean else 1

    def to_dict(self) -> Dict:
        return {
            "envelope": self.envelope,
            "engines": list(self.engines),
            "mutation": self.mutation,
            "clean": self.clean,
            "exit_code": self.exit_code,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [
                {**f.to_dict(), "justification": note}
                for f, note in self.suppressed
            ],
            "unjustified": [f.to_dict() for f in self.unjustified],
            "errors": list(self.errors),
            "coverage_gaps": {k: v for k, v in self.coverage_gaps.items() if v},
            "specs": list(self.specs),
            "exercised": sorted(self.exercised),
        }

    def render(self) -> str:
        lines: List[str] = []
        for f in self.findings:
            lines.append(f.render())
        for f in self.unjustified:
            lines.append(f.render())
        for eng, gaps in sorted(self.coverage_gaps.items()):
            for g in gaps:
                lines.append(
                    f"coverage[{eng}]: stage {g!r} observed at runtime but "
                    f"not covered by any audited spec")
        for e in self.errors:
            lines.append(f"error: {e}")
        n_sites = len({(f.path, f.line, f.rule) for f in self.findings})
        lines.append(
            f"scale-audit[{self.envelope}"
            + (f", mutate={self.mutation}" if self.mutation else "")
            + f"]: {len(self.specs)} stage specs over "
            f"{'/'.join(self.engines)} — "
            + (
                "proven clean"
                if self.clean
                else f"{len(self.findings)} finding(s) at {n_sites} site(s), "
                     f"{len(self.unjustified)} unjustified suppression(s), "
                     f"{sum(len(v) for v in self.coverage_gaps.values())} "
                     f"coverage gap(s), {len(self.errors)} error(s)"
            )
            + f"; {len(self.suppressed)} justified suppression(s)"
        )
        return "\n".join(lines)


# --------------------------------------------------------------------------
# suppression application


def _apply_suppressions(
    findings: Sequence[Finding],
) -> Tuple[List[Finding], List[Tuple[Finding, str]], List[Finding]]:
    """Split findings into (kept, suppressed-with-note, unjustified)."""
    cache: Dict[str, Dict[int, Tuple[set, str]]] = {}
    kept: List[Finding] = []
    suppressed: List[Tuple[Finding, str]] = []
    unjustified: List[Finding] = []
    for f in findings:
        notes = cache.get(f.path)
        if notes is None:
            try:
                with open(f.path, "r", encoding="utf-8") as fh:
                    notes = suppression_notes(fh.read())
            except OSError:
                notes = {}
            cache[f.path] = notes
        ids, note = notes.get(f.line, (set(), ""))
        if ids and (f.rule in ids or f.name in ids or "all" in ids):
            if note:
                suppressed.append((f, note))
            else:
                unjustified.append(dataclasses.replace(
                    f,
                    message=f.message + " [suppressed without justification "
                    "— the audit requires `-- <why it is safe>` after the "
                    "id list]",
                ))
        else:
            kept.append(f)
    return kept, suppressed, unjustified


# --------------------------------------------------------------------------
# driver


def _run_specs(env, specs, errors, exercised):
    findings: List[Finding] = []
    for spec in specs:
        try:
            closed, ivs = stages.trace_spec(spec, env)
        except Exception as exc:  # trace failure is an audit failure
            errors.append(f"{spec.spec_id}: trace failed: {exc!r}")
            continue
        axis = (
            stages.mesh_axis_sizes(env)
            if spec.spec_id.startswith("mesh.")
            else None
        )
        try:
            interpret_jaxpr(
                closed, ivs,
                stage=spec.spec_id,
                sentinels=env.sentinels,
                axis_sizes=axis,
                findings=findings,
                exercised=exercised,
            )
        except UnknownPrimitiveError as exc:
            errors.append(
                f"{spec.spec_id}: unknown primitive {exc.primitive!r} at "
                f"{exc.where} — no transfer function registered")
    return findings


def scale_audit(
    envelope: str = "baseline",
    engines: Optional[Sequence[str]] = None,
    *,
    overrides: Optional[Dict[str, int]] = None,
    check_coverage: bool = True,
    mutate: Optional[str] = None,
) -> AuditReport:
    """Run the full scale audit; see the module docstring.

    ``mutate`` replaces the catalog with the named seeded defect (the
    self-test: the report is *expected* dirty; exit code 1 proves the
    auditor catches it).
    """
    engines = tuple(engines) if engines else stages.ENGINES
    bad = set(engines) - set(stages.ENGINES)
    if bad:
        raise ValueError(f"unknown engines: {sorted(bad)}")
    env = get_envelope(envelope, overrides)

    errors: List[str] = []
    exercised: Set[str] = set()

    if mutate is not None:
        if mutate not in MUTATIONS:
            raise ValueError(
                f"unknown mutation {mutate!r} (have {sorted(MUTATIONS)})")
        desc, build = MUTATIONS[mutate]
        spec = stages.StageSpec(
            spec_id=f"mutation.{mutate}",
            stage_name=f"mutation.{mutate}",
            engines=engines,
            build=build,
        )
        raw = _run_specs(env, [spec], errors, exercised)
        # mutations are never suppressible: they live in this file, which
        # carries no swirld-lint comments
        kept, suppressed, unjustified = _apply_suppressions(raw)
        return AuditReport(
            envelope=env.name, engines=engines, findings=kept,
            suppressed=suppressed, unjustified=unjustified, errors=errors,
            coverage_gaps={}, specs=[spec.spec_id], exercised=exercised,
            mutation=mutate,
        )

    specs = stages.specs_for_engines(engines)
    raw = _run_specs(env, specs, errors, exercised)
    raw.extend(host_envelope_findings(env))
    kept, suppressed, unjustified = _apply_suppressions(raw)

    coverage_gaps: Dict[str, List[str]] = {}
    if check_coverage:
        cmap = stages.coverage_map()
        for eng in engines:
            try:
                observed = stages.observed_stage_names(eng)
            except Exception as exc:
                errors.append(f"coverage[{eng}]: runtime probe failed: "
                              f"{exc!r}")
                continue
            coverage_gaps[eng] = [s for s in observed if s not in cmap]

    return AuditReport(
        envelope=env.name, engines=engines, findings=kept,
        suppressed=suppressed, unjustified=unjustified, errors=errors,
        coverage_gaps=coverage_gaps,
        specs=[s.spec_id for s in specs], exercised=exercised,
    )


@functools.lru_cache(maxsize=4)
def _cached_stamp(envelope: str, engines: Tuple[str, ...]) -> Tuple:
    rep = scale_audit(envelope, engines, check_coverage=False)
    return (
        rep.clean,
        len(rep.findings) + len(rep.unjustified),
        len(rep.suppressed),
        len(rep.errors),
    )


def scale_audit_stamp(
    envelope: str = "baseline",
    engines: Optional[Sequence[str]] = None,
) -> Dict:
    """The shape bench.py stamps into JSON artifacts: whether the tree
    the benchmark ran from is proven scale-safe.  ``bench_compare.py``
    refuses to gate on an artifact whose stamp is dirty or missing.

    Coverage probing is skipped here (it runs real consensus workloads;
    the analyzer's own CI covers it) — the stamp is about *this tree's
    kernels*, cached per process since bench stamps several artifacts.
    """
    engines = tuple(engines) if engines else stages.ENGINES
    clean, n_findings, n_suppressed, n_errors = _cached_stamp(
        envelope, engines)
    return {
        "envelope": envelope,
        "engines": list(engines),
        "clean": clean,
        "findings": n_findings,
        "suppressed": n_suppressed,
        "errors": n_errors,
    }


# --------------------------------------------------------------------------
# CLI


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python -m tpu_swirld.analysis scale-audit",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument(
        "--envelope", default="baseline", choices=preset_names(),
        help="declared operating point to prove (default baseline)")
    ap.add_argument(
        "--engine", action="append", choices=list(stages.ENGINES),
        help="engine(s) to audit; repeatable (default all)")
    ap.add_argument(
        "--set", action="append", default=[], metavar="FIELD=VALUE",
        dest="overrides", help="override an envelope field (with "
        "--envelope custom); repeatable")
    ap.add_argument(
        "--mutate", choices=sorted(MUTATIONS),
        help="audit a seeded defect instead of the real stages (self-"
        "test: exit 1 proves the defect is caught)")
    ap.add_argument(
        "--no-coverage", action="store_true",
        help="skip the runtime stage-coverage probe")
    ap.add_argument("--json", action="store_true", help="machine output")
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print the flow rule catalog")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, name in sorted(RULE_NAMES.items()):
            print(f"{rid} {name}")
        return 0

    overrides: Dict[str, int] = {}
    for kv in args.overrides:
        k, sep, v = kv.partition("=")
        if not sep:
            ap.error(f"--set expects FIELD=VALUE, got {kv!r}")
        overrides[k.strip()] = int(v)

    rep = scale_audit(
        args.envelope,
        args.engine,
        overrides=overrides or None,
        check_coverage=not args.no_coverage and args.mutate is None,
        mutate=args.mutate,
    )
    if args.json:
        print(json.dumps(rep.to_dict(), indent=2))
    else:
        print(rep.render())
    return rep.exit_code


if __name__ == "__main__":
    import sys

    sys.exit(main())
