"""Per-primitive transfer functions over the interval×dtype lattice.

Every first-order primitive the consensus kernels use has an entry in
``TRANSFERS``; :func:`apply_transfer` dispatches an eqn through it and
then runs the centralized safety checks:

- **SW008** (overflow-reachable): the transfer computes the
  *mathematical* result interval in unbounded Python arithmetic; if an
  integer output's interval escapes its dtype range the site is
  reported, then the interval is clamped to the dtype range so one
  overflow doesn't cascade into a wall of downstream findings.  The
  same check covers the f32-tally exactness argument: an
  integer-valued float accumulation whose bound reaches 2**(mantissa+1)
  can no longer be exact, which is reported as SW008 and the
  ``integral`` flag dropped.
- **SW009** (unproven bounds): ``gather``/``scatter`` sites whose mode
  is ``PROMISE_IN_BOUNDS`` must have index intervals provably inside
  the operand extent (``CLIP``/``FILL_OR_DROP`` modes are runtime
  guards and pass).  ``dynamic_slice``/``dynamic_update_slice`` starts
  are checked against ``dim - slice_size`` — XLA clamps them, so the
  failure mode is a silently *wrong window*, not a crash, which is
  exactly why it must be proven statically.
- **SW010** (lossy narrowing): ``convert_element_type`` where the
  operand interval is not provably representable in the target dtype
  (including int→float casts past the float's exact-integer range).
- **SW011** (sentinel collision): ``select_n`` where one arm is a
  constant equal to a declared padding sentinel and another arm's
  interval contains that value — the sentinel becomes indistinguishable
  from live data.

Unknown primitives raise :class:`UnknownPrimitiveError` — the registry
never guesses (exit code 2 at the CLI; there is no "assume top" path).

``select_n`` performs pattern-based path refinement: when the predicate
is itself ``lt/le/gt/ge/eq(v, k)`` and an arm is ``v`` or ``v ± c`` of
the *same* variable, the arm's interval is first met with the branch
condition.  jnp lowers every ``x[i]`` through
``select_n(i < 0, i, i + n)`` for negative-index normalization, so
without this refinement every plain gather in the pipeline would be an
SW009 false positive.
"""

from __future__ import annotations

import numpy as np

from tpu_swirld.analysis.flow.lattice import (
    AbsVal,
    Interval,
    NEG_INF,
    POS_INF,
    dtype_range,
    is_bool_dtype,
    is_float_dtype,
    is_int_dtype,
    iv_abs,
    iv_add,
    iv_div_float,
    iv_div_int,
    iv_max,
    iv_min,
    iv_mul,
    iv_neg,
    iv_rem,
    iv_sub,
)


class UnknownPrimitiveError(Exception):
    """A primitive without a registered transfer function was reached."""

    def __init__(self, primitive: str, stage: str = "?", where: str = "?"):
        self.primitive = primitive
        self.stage = stage
        self.where = where
        super().__init__(
            f"no transfer function for primitive {primitive!r} "
            f"(stage {stage}, at {where}); the registry hard-fails rather "
            f"than guess — add a sound transfer to analysis/flow/transfer.py"
        )


TRANSFERS = {}

#: higher-order primitives the interpreter sub-interprets itself.
HIGHER_ORDER = frozenset(
    {"pjit", "closed_call", "core_call", "scan", "while", "cond", "shard_map",
     "custom_jvp_call", "custom_vjp_call", "remat", "checkpoint"}
)

#: primitives whose int results are accumulations — these also get the
#: integral-float exactness check (f32 tallies must stay < 2**24).
ACCUMULATING = frozenset(
    {"add", "sub", "mul", "dot_general", "reduce_sum", "cumsum", "cumprod",
     "scatter-add", "psum", "psum2"}
)

#: primitives that run their own representability check (skip SW008 there).
SELF_CHECKED = frozenset({"convert_element_type"})


def register(*names):
    def deco(fn):
        for n in names:
            TRANSFERS[n] = fn
        return fn
    return deco


def registered_primitives():
    """Sorted names of all first-order primitives with transfers."""
    return sorted(TRANSFERS)


def _out(eqn, j, iv, integral):
    return AbsVal.from_aval(eqn.outvars[j].aval, iv, integral)


def _exact_float_limit(dtype) -> int:
    return 1 << (np.finfo(np.dtype(dtype)).nmant + 1)


def apply_transfer(ctx, eqn, args):
    """Dispatch one eqn; returns out AbsVals, emits findings via ctx."""
    name = eqn.primitive.name
    fn = TRANSFERS.get(name)
    if fn is None:
        raise UnknownPrimitiveError(name, getattr(ctx, "stage", "?"),
                                    ctx.where(eqn))
    ctx.exercised.add(name)
    outs = fn(ctx, eqn, args)
    checked = []
    for j, o in enumerate(outs):
        if o.iv.is_bottom:
            checked.append(o)
            continue
        if is_int_dtype(o.dtype) and name not in SELF_CHECKED:
            lo, hi = dtype_range(o.dtype)
            if o.iv.lo < lo or o.iv.hi > hi:
                ctx.report(
                    "SW008", eqn,
                    f"{name}: {np.dtype(o.dtype).name} result can reach "
                    f"{o.iv}, outside [{lo}, {hi}] — integer wraparound "
                    f"reachable at this envelope",
                )
                o = o.clamp_to_dtype()
        elif (is_float_dtype(o.dtype) and o.integral
              and name in ACCUMULATING):
            lim = _exact_float_limit(o.dtype)
            m = max(abs(o.iv.lo), abs(o.iv.hi))
            if m >= lim:
                ctx.report(
                    "SW008", eqn,
                    f"{name}: integer-valued {np.dtype(o.dtype).name} "
                    f"accumulation can reach {o.iv}, at or past the exact-"
                    f"integer limit 2**{lim.bit_length() - 1} — tally no "
                    f"longer exact",
                )
                o = o.with_iv(o.iv, integral=False)
        checked.append(o)
    return checked


# --------------------------------------------------------------------------
# elementwise arithmetic


@register("add")
def _t_add(ctx, eqn, args):
    a, b = args
    return [_out(eqn, 0, iv_add(a.iv, b.iv), a.integral and b.integral)]


@register("sub")
def _t_sub(ctx, eqn, args):
    a, b = args
    return [_out(eqn, 0, iv_sub(a.iv, b.iv), a.integral and b.integral)]


@register("mul")
def _t_mul(ctx, eqn, args):
    a, b = args
    return [_out(eqn, 0, iv_mul(a.iv, b.iv), a.integral and b.integral)]


@register("neg")
def _t_neg(ctx, eqn, args):
    (a,) = args
    return [_out(eqn, 0, iv_neg(a.iv), a.integral)]


@register("abs")
def _t_abs(ctx, eqn, args):
    (a,) = args
    return [_out(eqn, 0, iv_abs(a.iv), a.integral)]


@register("sign")
def _t_sign(ctx, eqn, args):
    (a,) = args
    lo = -1 if a.iv.lo < 0 else (0 if a.iv.lo == 0 else 1)
    hi = 1 if a.iv.hi > 0 else (0 if a.iv.hi == 0 else -1)
    return [_out(eqn, 0, Interval(lo, hi), True)]


@register("div")
def _t_div(ctx, eqn, args):
    a, b = args
    if is_int_dtype(eqn.outvars[0].aval.dtype):
        return [_out(eqn, 0, iv_div_int(a.iv, b.iv), True)]
    return [_out(eqn, 0, iv_div_float(a.iv, b.iv), False)]


@register("rem")
def _t_rem(ctx, eqn, args):
    a, b = args
    return [_out(eqn, 0, iv_rem(a.iv, b.iv), a.integral and b.integral)]


@register("max")
def _t_max(ctx, eqn, args):
    a, b = args
    return [_out(eqn, 0, iv_max(a.iv, b.iv), a.integral and b.integral)]


@register("min")
def _t_min(ctx, eqn, args):
    a, b = args
    return [_out(eqn, 0, iv_min(a.iv, b.iv), a.integral and b.integral)]


@register("clamp")
def _t_clamp(ctx, eqn, args):
    lo_v, x, hi_v = args
    iv = iv_min(iv_max(x.iv, lo_v.iv), hi_v.iv)
    return [_out(eqn, 0, iv, x.integral and lo_v.integral and hi_v.integral)]


@register("integer_pow")
def _t_integer_pow(ctx, eqn, args):
    (a,) = args
    y = int(eqn.params["y"])
    iv = Interval.point(1)
    for _ in range(abs(y)):
        iv = iv_mul(iv, a.iv)
    if y < 0:
        iv = iv_div_float(Interval.point(1.0), iv)
    return [_out(eqn, 0, iv, a.integral and y >= 0)]


# --------------------------------------------------------------------------
# boolean / bitwise


def _bitlen(v):
    if v in (POS_INF, NEG_INF):
        return None
    return int(v).bit_length()


@register("and")
def _t_and(ctx, eqn, args):
    a, b = args
    if is_bool_dtype(eqn.outvars[0].aval.dtype):
        return [_out(eqn, 0, iv_min(a.iv, b.iv).meet(Interval(0, 1)), True)]
    if a.iv.lo >= 0 and b.iv.lo >= 0:
        return [_out(eqn, 0, Interval(0, min(a.iv.hi, b.iv.hi)), True)]
    return [_out(eqn, 0, AbsVal.from_aval(eqn.outvars[0].aval).iv, True)]


@register("or")
def _t_or(ctx, eqn, args):
    a, b = args
    if is_bool_dtype(eqn.outvars[0].aval.dtype):
        return [_out(eqn, 0, iv_max(a.iv, b.iv).meet(Interval(0, 1)), True)]
    if a.iv.lo >= 0 and b.iv.lo >= 0:
        ba, bb = _bitlen(a.iv.hi), _bitlen(b.iv.hi)
        if ba is None or bb is None:
            return [_out(eqn, 0, AbsVal.from_aval(eqn.outvars[0].aval).iv, True)]
        hi = (1 << max(ba, bb)) - 1
        return [_out(eqn, 0, Interval(max(a.iv.lo, b.iv.lo), max(hi, 0)), True)]
    return [_out(eqn, 0, AbsVal.from_aval(eqn.outvars[0].aval).iv, True)]


@register("xor")
def _t_xor(ctx, eqn, args):
    a, b = args
    if is_bool_dtype(eqn.outvars[0].aval.dtype):
        return [_out(eqn, 0, Interval(0, 1), True)]
    if a.iv.lo >= 0 and b.iv.lo >= 0:
        ba, bb = _bitlen(a.iv.hi), _bitlen(b.iv.hi)
        if ba is not None and bb is not None:
            return [_out(eqn, 0, Interval(0, (1 << max(ba, bb)) - 1), True)]
    return [_out(eqn, 0, AbsVal.from_aval(eqn.outvars[0].aval).iv, True)]


@register("not")
def _t_not(ctx, eqn, args):
    (a,) = args
    if is_bool_dtype(eqn.outvars[0].aval.dtype):
        return [_out(eqn, 0, Interval(0, 1), True)]
    return [_out(eqn, 0, Interval(-a.iv.hi - 1, -a.iv.lo - 1), True)]


def _cmp_decide(op: str, a: Interval, b: Interval) -> Interval:
    """Fold a comparison to a point when the operand intervals decide it
    for every element (whole-array abstraction: a decided interval
    comparison is decided element-wise)."""
    if a.is_bottom or b.is_bottom:
        return Interval(0, 1)
    if op == "lt":
        if a.hi < b.lo:
            return Interval.point(1)
        if a.lo >= b.hi:
            return Interval.point(0)
    elif op == "le":
        if a.hi <= b.lo:
            return Interval.point(1)
        if a.lo > b.hi:
            return Interval.point(0)
    elif op == "gt":
        if a.lo > b.hi:
            return Interval.point(1)
        if a.hi <= b.lo:
            return Interval.point(0)
    elif op == "ge":
        if a.lo >= b.hi:
            return Interval.point(1)
        if a.hi < b.lo:
            return Interval.point(0)
    elif op == "eq":
        if a.is_point and b.is_point and a.lo == b.lo:
            return Interval.point(1)
        if a.hi < b.lo or b.hi < a.lo:
            return Interval.point(0)
    elif op == "ne":
        if a.is_point and b.is_point and a.lo == b.lo:
            return Interval.point(0)
        if a.hi < b.lo or b.hi < a.lo:
            return Interval.point(1)
    return Interval(0, 1)


def _cmp(eqn, args, op):
    a, b = args
    return [_out(eqn, 0, _cmp_decide(op, a.iv, b.iv), True)]


@register("eq")
def _t_eq(ctx, eqn, args):
    return _cmp(eqn, args, "eq")


@register("ne")
def _t_ne(ctx, eqn, args):
    return _cmp(eqn, args, "ne")


@register("lt")
def _t_lt(ctx, eqn, args):
    return _cmp(eqn, args, "lt")


@register("le")
def _t_le(ctx, eqn, args):
    return _cmp(eqn, args, "le")


@register("gt")
def _t_gt(ctx, eqn, args):
    return _cmp(eqn, args, "gt")


@register("ge")
def _t_ge(ctx, eqn, args):
    return _cmp(eqn, args, "ge")


# --------------------------------------------------------------------------
# select_n with path refinement + sentinel-collision check (SW011)

_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}


def _refine_by_pred(v_iv: Interval, op: str, k_iv: Interval, branch: bool) -> Interval:
    """Interval of v inside the branch where ``op(v, k)`` is `branch`."""
    if op == "lt":
        cond_true = Interval(NEG_INF, k_iv.hi - 1 if isinstance(k_iv.hi, int) else k_iv.hi)
        cond_false = Interval(k_iv.lo, POS_INF)
    elif op == "le":
        cond_true = Interval(NEG_INF, k_iv.hi)
        cond_false = Interval(k_iv.lo + 1 if isinstance(k_iv.lo, int) else k_iv.lo, POS_INF)
    elif op == "gt":
        cond_true = Interval(k_iv.lo + 1 if isinstance(k_iv.lo, int) else k_iv.lo, POS_INF)
        cond_false = Interval(NEG_INF, k_iv.hi)
    elif op == "ge":
        cond_true = Interval(k_iv.lo, POS_INF)
        cond_false = Interval(NEG_INF, k_iv.hi - 1 if isinstance(k_iv.hi, int) else k_iv.hi)
    elif op == "eq":
        cond_true = k_iv
        cond_false = Interval(NEG_INF, POS_INF)
    else:
        return v_iv
    return v_iv.meet(cond_true if branch else cond_false)


def _peel(ctx, atom):
    """Follow value-preserving ``convert_element_type`` chains back to the
    underlying variable (jnp's index normalization converts to int64
    before adding the axis size)."""
    import jax.core as jcore

    for _ in range(8):
        if isinstance(atom, jcore.Literal):
            break
        d = ctx.defs.get(atom)
        if d is None or d.primitive.name != "convert_element_type":
            break
        atom = d.invars[0]
    return atom


def _same_var(a, b):
    return a is b or (hasattr(a, "count") and a == b)


def _case_as_offset_of(ctx, case_atom, base_var):
    """If `case` is `base`, or add/sub of `base` and a constant, return the
    constant offset interval; else None.  Converts between int dtypes are
    peeled on both sides."""
    import jax.core as jcore

    case_atom = _peel(ctx, case_atom)
    if isinstance(case_atom, jcore.Literal):
        return None
    if _same_var(case_atom, base_var):
        return Interval.point(0)
    d = ctx.defs.get(case_atom)
    if d is None or d.primitive.name not in ("add", "sub"):
        return None
    x, y = d.invars
    for var, const, sign in ((x, y, 1), (y, x, 1 if d.primitive.name == "add" else None)):
        if sign is None:
            continue
        if _same_var(_peel(ctx, var), base_var):
            k = ctx.const_interval(const)
            if k is None:
                return None
            return k if d.primitive.name == "add" else iv_neg(k)
    return None


@register("select_n")
def _t_select_n(ctx, eqn, args):
    import jax.core as jcore

    pred, cases = args[0], args[1:]
    out_dt = eqn.outvars[0].aval.dtype

    # Decided predicate: only the selected arm is reachable, so the
    # unselected arms contribute nothing (and cannot collide with a
    # sentinel).  Covers jnp's negative-index normalization when the
    # index interval never crosses zero.
    p_iv = pred.iv
    if p_iv.is_point and isinstance(p_iv.lo, int):
        idx = int(p_iv.lo)
        if 0 <= idx < len(cases):
            sel = cases[idx]
            return [_out(eqn, 0, sel.iv, sel.integral)]

    # Path refinement for the 2-case boolean select where the predicate
    # compares a variable against a constant and an arm is an affine
    # offset of that same variable (jnp's negative-index normalization,
    # and guard patterns like where(i < cap, i, cap - 1)).
    refined = None
    if len(cases) == 2 and not isinstance(eqn.invars[0], jcore.Literal):
        pd = ctx.defs.get(eqn.invars[0])
        if pd is not None and pd.primitive.name in _FLIP:
            op = pd.primitive.name
            lhs, rhs = pd.invars
            k_iv = ctx.const_interval(rhs)
            base = _peel(ctx, lhs)
            if k_iv is None:
                k_iv = ctx.const_interval(lhs)
                base = _peel(ctx, rhs)
                op = _FLIP[op]
            if k_iv is not None and not isinstance(base, jcore.Literal):
                base_iv = ctx.env_lookup(base)
                if base_iv is not None:
                    parts = []
                    for which, case_atom, case_val in (
                        (False, eqn.invars[1], cases[0]),
                        (True, eqn.invars[2], cases[1]),
                    ):
                        off = _case_as_offset_of(ctx, case_atom, base)
                        if off is not None:
                            br = _refine_by_pred(base_iv.iv, op, k_iv, which)
                            parts.append(
                                Interval.bottom() if br.is_bottom
                                else iv_add(br, off))
                        else:
                            parts.append(case_val.iv)
                    iv = parts[0].join(parts[1])
                    refined = iv

    if refined is None:
        iv = Interval.bottom()
        for c in cases:
            iv = iv.join(c.iv)

    # SW011: one arm a constant sentinel, another arm's live range
    # containing that very value.
    if is_int_dtype(out_dt):
        for sval in ctx.sentinels:
            if not any(c.iv.is_point and c.iv.lo == sval for c in cases):
                continue
            for c in cases:
                if c.iv.is_point and c.iv.lo == sval:
                    continue
                if c.iv.contains(sval):
                    ctx.report(
                        "SW011", eqn,
                        f"select_n: one arm is the padding sentinel {sval} "
                        f"and another arm's range {c.iv} contains it — "
                        f"sentinel can collide with live data",
                    )
                    break

    integral = all(c.integral for c in cases)
    return [_out(eqn, 0, iv, integral)]


# --------------------------------------------------------------------------
# dtype conversion (SW010)


@register("convert_element_type")
def _t_convert(ctx, eqn, args):
    (a,) = args
    new_dt = np.dtype(eqn.params["new_dtype"])
    iv = a.iv
    integral = a.integral
    if is_int_dtype(new_dt) or is_bool_dtype(new_dt):
        lo, hi = dtype_range(new_dt)
        src_lo = a.iv.lo if a.integral else np.floor(a.iv.lo) if a.iv.lo not in (NEG_INF,) else NEG_INF
        src_hi = a.iv.hi if a.integral else np.ceil(a.iv.hi) if a.iv.hi not in (POS_INF,) else POS_INF
        if is_bool_dtype(new_dt):
            if a.iv.is_point and a.iv.lo == 0:
                iv = Interval(0, 0)
            elif not a.iv.contains(0):
                iv = Interval(1, 1)
            else:
                iv = Interval(0, 1)
            return [_out(eqn, 0, iv, True)]
        if src_lo < lo or src_hi > hi:
            ctx.report(
                "SW010", eqn,
                f"convert_element_type: narrowing to {new_dt.name} loses "
                f"values — operand range {a.iv} exceeds [{lo}, {hi}]",
            )
        iv = Interval(
            max(lo, int(src_lo) if src_lo not in (NEG_INF, POS_INF) else lo),
            min(hi, int(src_hi) if src_hi not in (NEG_INF, POS_INF) else hi),
        )
        if iv.is_bottom:
            iv = Interval(lo, hi)
        integral = True
    elif is_float_dtype(new_dt):
        if a.integral and is_int_dtype(np.dtype(a.dtype)):
            lim = _exact_float_limit(new_dt)
            m = max(abs(a.iv.lo), abs(a.iv.hi))
            if m >= lim:
                ctx.report(
                    "SW010", eqn,
                    f"convert_element_type: int→{new_dt.name} cast of range "
                    f"{a.iv} passes the exact-integer limit 2**"
                    f"{lim.bit_length() - 1} — values rounded",
                )
                integral = False
        iv = Interval(float(a.iv.lo) if a.iv.lo not in (NEG_INF, POS_INF) else a.iv.lo,
                      float(a.iv.hi) if a.iv.hi not in (NEG_INF, POS_INF) else a.iv.hi)
    return [_out(eqn, 0, iv, integral)]


# --------------------------------------------------------------------------
# shape-only / structural


def _passthrough(ctx, eqn, args):
    a = args[0]
    return [_out(eqn, 0, a.iv, a.integral)]


for _name in ("broadcast_in_dim", "reshape", "squeeze", "expand_dims",
              "transpose", "rev", "copy", "slice", "stop_gradient",
              "reduce_precision", "pbroadcast", "pcast"):
    register(_name)(_passthrough)


@register("concatenate")
def _t_concat(ctx, eqn, args):
    iv = Interval.bottom()
    integral = True
    for a in args:
        if a.size:
            iv = iv.join(a.iv)
            integral = integral and a.integral
    return [_out(eqn, 0, iv, integral)]


@register("pad")
def _t_pad(ctx, eqn, args):
    a, pv = args
    return [_out(eqn, 0, a.iv.join(pv.iv), a.integral and pv.integral)]


@register("iota")
def _t_iota(ctx, eqn, args):
    dim = eqn.params["dimension"]
    n = eqn.outvars[0].aval.shape[dim]
    return [_out(eqn, 0, Interval(0, max(n - 1, 0)), True)]


@register("sort")
def _t_sort(ctx, eqn, args):
    return [_out(eqn, j, a.iv, a.integral) for j, a in enumerate(args)]


# --------------------------------------------------------------------------
# reductions


def _reduced_count(operand_shape, axes):
    n = 1
    for ax in axes:
        n *= operand_shape[ax]
    return max(n, 1)


@register("reduce_sum")
def _t_reduce_sum(ctx, eqn, args):
    (a,) = args
    n = _reduced_count(a.shape, eqn.params["axes"])
    # sum of exactly n elements, each in [lo, hi], is [n*lo, n*hi]
    return [_out(eqn, 0, Interval(a.iv.lo * n, a.iv.hi * n), a.integral)]


@register("reduce_max")
def _t_reduce_max(ctx, eqn, args):
    (a,) = args
    return [_out(eqn, 0, a.iv, a.integral)]


@register("reduce_min")
def _t_reduce_min(ctx, eqn, args):
    (a,) = args
    return [_out(eqn, 0, a.iv, a.integral)]


@register("reduce_and")
def _t_reduce_and(ctx, eqn, args):
    return [_out(eqn, 0, Interval(0, 1), True)]


@register("reduce_or")
def _t_reduce_or(ctx, eqn, args):
    return [_out(eqn, 0, Interval(0, 1), True)]


@register("argmax", "argmin")
def _t_argminmax(ctx, eqn, args):
    (a,) = args
    n = _reduced_count(a.shape, eqn.params["axes"])
    return [_out(eqn, 0, Interval(0, max(n - 1, 0)), True)]


@register("cumsum")
def _t_cumsum(ctx, eqn, args):
    (a,) = args
    n = a.shape[eqn.params["axis"]] if a.shape else 1
    lo = min(a.iv.lo, a.iv.lo * n)
    hi = max(a.iv.hi, a.iv.hi * n)
    return [_out(eqn, 0, Interval(lo, hi), a.integral)]


@register("cumprod")
def _t_cumprod(ctx, eqn, args):
    (a,) = args
    n = a.shape[eqn.params["axis"]] if a.shape else 1
    lo, hi = a.iv.lo, a.iv.hi
    if lo >= 0 and hi <= 1:
        iv = Interval(0 if lo < 1 else 1, hi)
    elif lo >= -1 and hi <= 1:
        m = max(abs(lo), abs(hi))
        iv = Interval(-m, m)
    else:
        m = max(abs(lo), abs(hi))
        try:
            big = m ** n if m not in (POS_INF,) else POS_INF
        except OverflowError:
            big = POS_INF
        iv = Interval(0 if lo >= 0 else -big, big)
    return [_out(eqn, 0, iv, a.integral)]


@register("dot_general")
def _t_dot_general(ctx, eqn, args):
    a, b = args
    (lhs_c, _), _ = eqn.params["dimension_numbers"]
    k = 1
    for d in lhs_c:
        k *= a.shape[d]
    k = max(k, 1)
    p = iv_mul(a.iv, b.iv)
    # sum of exactly k products, each in [p.lo, p.hi]
    return [_out(eqn, 0, Interval(p.lo * k, p.hi * k),
                 a.integral and b.integral)]


# --------------------------------------------------------------------------
# indexing (SW009)

_PROMISE = "PROMISE_IN_BOUNDS"


def _mode_name(mode) -> str:
    return getattr(mode, "name", str(mode) if mode is not None else "CLIP")


def _check_index_bounds(ctx, eqn, idx: AbsVal, allowed_hi: int, what: str):
    if idx.iv.is_bottom:
        return
    if idx.iv.lo < 0 or idx.iv.hi > allowed_hi:
        ctx.report(
            "SW009", eqn,
            f"{what}: index range {idx.iv} not provably within "
            f"[0, {allowed_hi}] — out-of-bounds access unproven at this "
            f"envelope",
        )


def _index_component_ivs(ctx, idx_atom, idx_val, n_comp):
    """Per-component intervals of a gather/scatter index array.

    jnp's advanced indexing stacks heterogeneous index vectors with a
    ``concatenate`` along the trailing (index-vector) dim; without this,
    the whole-array interval is the join of all components and a row
    index gets checked against the column bound."""
    import jax.core as jcore

    atom = idx_atom
    d = None
    for _ in range(4):
        if isinstance(atom, jcore.Literal):
            break
        dd = ctx.defs.get(atom)
        if dd is None:
            break
        if dd.primitive.name in ("convert_element_type", "copy"):
            atom = dd.invars[0]
            continue
        d = dd
        break
    if (
        d is None
        or d.primitive.name != "concatenate"
        or d.params.get("dimension") != len(idx_val.shape) - 1
    ):
        return [idx_val.iv] * n_comp
    comps = []
    for piece in d.invars:
        pv = ctx.env_lookup(piece)
        if pv is None:
            return [idx_val.iv] * n_comp
        comps.extend([pv.iv] * piece.aval.shape[-1])
    if len(comps) != n_comp:
        return [idx_val.iv] * n_comp
    return comps


@register("gather")
def _t_gather(ctx, eqn, args):
    operand, idx = args
    dn = eqn.params["dimension_numbers"]
    slice_sizes = eqn.params["slice_sizes"]
    mode = _mode_name(eqn.params.get("mode"))
    n_comp = len(dn.start_index_map)
    if idx.shape and idx.shape[-1] == n_comp:
        comp_ivs = _index_component_ivs(ctx, eqn.invars[1], idx, n_comp)
    else:
        comp_ivs = [idx.iv] * n_comp
    in_bounds = True
    for j, d in enumerate(dn.start_index_map):
        a_hi = operand.shape[d] - slice_sizes[d]
        civ = comp_ivs[j]
        if civ.is_bottom or (civ.lo >= 0 and civ.hi <= a_hi):
            continue
        in_bounds = False
        if mode == _PROMISE:
            ctx.report(
                "SW009", eqn,
                f"gather(mode=promise_in_bounds): index range {civ} "
                f"(operand dim {d}) not provably within [0, {a_hi}] — "
                f"out-of-bounds access unproven at this envelope",
            )
    iv = operand.iv
    integral = operand.integral
    if mode == "FILL_OR_DROP" and not in_bounds:
        fv = eqn.params.get("fill_value")
        if fv is not None:
            iv = iv.join(Interval.point(
                int(fv) if is_int_dtype(operand.dtype) else float(fv)))
        else:
            lo, hi = dtype_range(operand.dtype)
            iv = iv.join(Interval(lo, hi))
    return [_out(eqn, 0, iv, integral)]


def _scatter_common(ctx, eqn, args, additive):
    operand, idx, upd = args
    dn = eqn.params["dimension_numbers"]
    mode = _mode_name(eqn.params.get("mode"))
    if mode == _PROMISE:
        dims = dn.scatter_dims_to_operand_dims
        if idx.shape and idx.shape[-1] == len(dims):
            comp_ivs = _index_component_ivs(ctx, eqn.invars[1], idx, len(dims))
        else:
            comp_ivs = [idx.iv] * len(dims)
        for j, d in enumerate(dims):
            a_hi = operand.shape[d] - 1
            civ = comp_ivs[j]
            if civ.is_bottom or (civ.lo >= 0 and civ.hi <= a_hi):
                continue
            ctx.report(
                "SW009", eqn,
                f"scatter(mode=promise_in_bounds): index range {civ} "
                f"(operand dim {d}) not provably within [0, {a_hi}] — "
                f"out-of-bounds access unproven at this envelope",
            )
    if additive:
        # worst case every update row lands on one slot
        n_upd = 1
        for i, d in enumerate(upd.shape):
            if i not in dn.update_window_dims:
                n_upd *= d
        if eqn.params.get("unique_indices"):
            n_upd = 1
        n_upd = max(n_upd, 1)
        delta = Interval(min(0, upd.iv.lo) * n_upd, max(0, upd.iv.hi) * n_upd)
        iv = iv_add(operand.iv, delta)
    else:
        iv = operand.iv.join(upd.iv)
    return [_out(eqn, 0, iv, operand.integral and upd.integral)]


@register("scatter")
def _t_scatter(ctx, eqn, args):
    return _scatter_common(ctx, eqn, args, additive=False)


@register("scatter-add")
def _t_scatter_add(ctx, eqn, args):
    return _scatter_common(ctx, eqn, args, additive=True)


@register("dynamic_slice")
def _t_dynamic_slice(ctx, eqn, args):
    operand, starts = args[0], args[1:]
    sizes = eqn.params["slice_sizes"]
    for i, s in enumerate(starts):
        allowed = operand.shape[i] - sizes[i]
        if not s.iv.is_bottom and (s.iv.lo < 0 or s.iv.hi > allowed):
            _check_index_bounds(
                ctx, eqn, s, allowed,
                f"dynamic_slice start (dim {i}, extent {operand.shape[i]}, "
                f"size {sizes[i]}; XLA clamps, so an unproven start reads a "
                f"silently shifted window)")
    return [_out(eqn, 0, operand.iv, operand.integral)]


@register("dynamic_update_slice")
def _t_dynamic_update_slice(ctx, eqn, args):
    operand, upd, starts = args[0], args[1], args[2:]
    for i, s in enumerate(starts):
        allowed = operand.shape[i] - upd.shape[i]
        if not s.iv.is_bottom and (s.iv.lo < 0 or s.iv.hi > allowed):
            _check_index_bounds(
                ctx, eqn, s, allowed,
                f"dynamic_update_slice start (dim {i}, extent "
                f"{operand.shape[i]}, update {upd.shape[i]}; XLA clamps, so "
                f"an unproven start writes a silently shifted window)")
    return [_out(eqn, 0, operand.iv.join(upd.iv),
                 operand.integral and upd.integral)]


# --------------------------------------------------------------------------
# mesh collectives


@register("psum", "psum2")
def _t_psum(ctx, eqn, args):
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    n = 1
    for ax in (axes if isinstance(axes, (tuple, list)) else (axes,)):
        if isinstance(ax, str):
            n *= ctx.axis_sizes.get(ax, 1)
        else:
            n *= int(ax)
    n = max(n, 1)
    outs = []
    for j, a in enumerate(args):
        outs.append(_out(eqn, j, Interval(a.iv.lo * n, a.iv.hi * n), a.integral))
    return outs


@register("axis_index")
def _t_axis_index(ctx, eqn, args):
    ax = eqn.params["axis_name"]
    n = ctx.axis_sizes.get(ax, 1)
    return [_out(eqn, 0, Interval(0, max(n - 1, 0)), True)]
