"""Scale envelopes: the declared operating points the audit proves safe.

An envelope is a *claim about inputs*: how many events, members, window
columns, rounds-in-flight, fork groups, how large a stake or timestamp
can get.  The auditor traces every stage at the envelope's shapes and
seeds the interpreter with the envelope's value intervals; everything
downstream is then *derived*, so "no int32 wraps at 1M events" is a
theorem about the envelope, not a hope about test data.

Presets:

``baseline``
    the tier-1 / bench operating point — 8 members, 4k events, default
    window buckets.  Fast to trace; run by ``scripts/lint.sh``.

``1m``
    ROADMAP item 4's target — 2**20 events, 256 members, grown window
    buckets, per-member stake up to 2**15 (so total stake stays under
    the 2**24 exact-f32 tally limit the pipeline's GEMM path is gated
    on), timestamps strictly below ``INT32_MAX`` (the order-stage
    sentinel — the packer enforces this bound on ingest).

``custom``
    ``1m`` with ``--set field=value`` overrides from the CLI.

Envelope invariants that are *checked here* (host-side closed-form,
because the store/packing layers are numpy, not jaxprs) live in
:func:`host_envelope_findings`: packed-dtype headroom for event counts,
timestamp-vs-sentinel headroom, stake totals vs the exact-f32 limit,
and archive block-offset arithmetic.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from tpu_swirld.analysis.lint import Finding

INT32_MAX = int(np.iinfo(np.int32).max)

#: exact-integer limit of float32 (the pipeline's fused-GEMM gate)
F32_EXACT = 1 << 24


@dataclasses.dataclass(frozen=True)
class ScaleEnvelope:
    """Declared operating point for the scale audit."""

    name: str
    events: int          # total events ingested (N)
    members: int         # member count (M)
    rows: int            # resident window rows after bucket growth
    wcols: int           # witness/window column cap (_wcol_cap growth)
    chunk: int           # ingest chunk
    block: int           # ssm block tile
    r_cap: int           # rounds-in-flight cap in the window tables
    s_cap: int           # slots per round (forks: members + 1)
    k_cap: int           # fork-tips per member cap
    chain_cap: int       # self-parent chain walk cap
    fork_groups: int     # fork accusation table rows (G)
    stake_max: int       # per-member stake bound
    t_max: int           # timestamp bound (strictly below the sentinel)
    coin_period: int = 6
    mesh_devices: int = 8
    sentinels: Tuple[int, ...] = (INT32_MAX,)

    @property
    def tot_stake(self) -> int:
        return self.members * self.stake_max

    def to_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        d["tot_stake"] = self.tot_stake
        return d


_PRESETS: Dict[str, ScaleEnvelope] = {
    "baseline": ScaleEnvelope(
        name="baseline",
        events=4096,
        members=8,
        rows=2048,
        wcols=256,
        chunk=128,
        block=128,
        r_cap=32,
        s_cap=9,
        k_cap=8,
        chain_cap=32,
        fork_groups=64,
        stake_max=64,
        t_max=1 << 24,
    ),
    "1m": ScaleEnvelope(
        name="1m",
        events=1 << 20,
        members=256,
        rows=16384,
        wcols=1024,
        chunk=256,
        block=128,
        r_cap=64,
        s_cap=257,
        k_cap=8,
        chain_cap=64,
        fork_groups=256,
        stake_max=1 << 15,
        t_max=INT32_MAX - 1,
    ),
}


def get_envelope(name: str,
                 overrides: Optional[Dict[str, int]] = None) -> ScaleEnvelope:
    """Resolve a preset (``baseline``/``1m``) or ``custom`` (= ``1m`` plus
    ``overrides``)."""
    if name == "custom":
        base = _PRESETS["1m"]
        fields = {f.name for f in dataclasses.fields(ScaleEnvelope)}
        bad = set(overrides or ()) - fields
        if bad:
            raise ValueError(f"unknown envelope fields: {sorted(bad)}")
        return dataclasses.replace(base, name="custom", **(overrides or {}))
    if name not in _PRESETS:
        raise ValueError(
            f"unknown envelope {name!r} (baseline | 1m | custom)")
    if overrides:
        return dataclasses.replace(_PRESETS[name], **overrides)
    return _PRESETS[name]


def preset_names() -> List[str]:
    return sorted(_PRESETS) + ["custom"]


# --------------------------------------------------------------------------
# host-side closed-form checks (store/ and packing are numpy, not jaxprs)


def _finding(rule, path, msg, line=0):
    from tpu_swirld.analysis.flow.interpret import RULE_NAMES

    return Finding(rule, RULE_NAMES.get(rule, rule), path, line, 0, msg)


def host_envelope_findings(env: ScaleEnvelope) -> List[Finding]:
    """Closed-form envelope checks for the host-side (numpy) layers.

    These mirror what the jaxpr interpreter proves for device code:
    every packed int32 field, archive offset product, and sentinel
    comparison is evaluated symbolically at the envelope bounds.
    """
    out: List[Finding] = []
    N, M = env.events, env.members

    # packing.py: event ids, parent ids, creator, seq are int32.
    for what, hi in (
        ("event index / parent id", N - 1),
        ("creator index", M - 1),
        ("per-creator seq", N - 1),
    ):
        if hi > INT32_MAX:
            out.append(_finding(
                "SW008", "tpu_swirld/packing.py",
                f"envelope {env.name}: {what} can reach {hi}, outside "
                f"int32 — packed columns wrap"))

    # packing.py: timestamps are compared against the INT32_MAX order
    # sentinel on device; the packer must keep them strictly below it.
    if env.t_max >= min(env.sentinels, default=INT32_MAX):
        out.append(_finding(
            "SW011", "tpu_swirld/packing.py",
            f"envelope {env.name}: timestamp bound {env.t_max} reaches the "
            f"order-stage sentinel {min(env.sentinels)} — a live timestamp "
            f"becomes indistinguishable from padding"))

    # pipeline GEMM gate: integer tallies carried in f32 stay exact only
    # below 2**24 (checked at runtime by tot_stake < (1 << 24); the
    # envelope must satisfy it statically too).
    if env.tot_stake >= F32_EXACT:
        out.append(_finding(
            "SW008", "tpu_swirld/tpu/pipeline.py",
            f"envelope {env.name}: total stake {env.tot_stake} reaches the "
            f"exact-f32 limit 2**24 — fused GEMM tally path loses votes"))

    # supermajority arithmetic 3*acc vs 2*tot in int32
    if 3 * env.tot_stake > INT32_MAX:
        out.append(_finding(
            "SW008", "tpu_swirld/tpu/pipeline.py",
            f"envelope {env.name}: 3*tot_stake = {3 * env.tot_stake} wraps "
            f"int32 in the supermajority comparison"))

    # store/slab + archive: byte offsets of the largest slab (rows x
    # wcols int32 plus bool planes) must fit in int64 (numpy indexing)
    # and element counts in int32 where stored as int32 columns.
    slab_elems = env.rows * max(env.wcols, M)
    if slab_elems > INT32_MAX:
        out.append(_finding(
            "SW008", "tpu_swirld/store/slab.py",
            f"envelope {env.name}: slab element count {slab_elems} exceeds "
            f"int32 — int32 column indexing wraps"))
    archive_bytes = N * (2 + 1 + 1 + 1) * 4 + N * 8  # packed cols + t int64
    if archive_bytes > (1 << 62):
        out.append(_finding(
            "SW008", "tpu_swirld/store/archive.py",
            f"envelope {env.name}: archive byte extent {archive_bytes} "
            f"overflows int64 offsets"))

    # window bookkeeping: rows grow in buckets; a full window of wcols
    # witness columns indexed by int32 column ids.
    if env.rows > INT32_MAX or env.wcols > INT32_MAX:
        out.append(_finding(
            "SW008", "tpu_swirld/tpu/pipeline.py",
            f"envelope {env.name}: window extents ({env.rows} x {env.wcols}) "
            f"exceed int32 indexing"))
    return out
