"""CLI dispatcher: ``python -m tpu_swirld.analysis <subcommand>``.

Subcommands::

    lint        [paths...] [--json] [--rules ...] [--list-rules]
    jit-audit   [--static-only] [--members N] [--events N] [--engine E] [--json]
    races       [--schedules N] [--seed S] [--rows N] [--json]
    mc          [--n N] [--events N] [--forkers N] [--mutate NAME] [--json]
    scale-audit [--envelope E] [--engine E] [--set F=V] [--mutate NAME] [--json]

Each exits non-zero on findings / audit failures / schedule divergence /
invariant violations, so all five slot directly into CI.
"""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0 if argv else 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "lint":
        from tpu_swirld.analysis.lint import main as m
    elif cmd == "jit-audit":
        from tpu_swirld.analysis.jit_audit import main as m
    elif cmd == "races":
        from tpu_swirld.analysis.races import main as m
    elif cmd == "mc":
        from tpu_swirld.analysis.mc.cli import main as m
    elif cmd == "scale-audit":
        from tpu_swirld.analysis.flow.audit import main as m
    else:
        print(f"unknown subcommand {cmd!r} "
              f"(lint | jit-audit | races | mc | scale-audit)")
        return 2
    return m(rest)


if __name__ == "__main__":
    sys.exit(main())
