"""Static analysis & sanitizers for the consensus core.

Three invariant classes hold in this codebase only by convention, and a
single unnoticed violation of any of them is a latent consensus-safety or
performance bug:

- **Determinism** — consensus safety is "decided prefixes bit-identical
  across nodes".  Unseeded RNG, hash-randomized ``set`` iteration
  (PYTHONHASHSEED), or a wall-clock read on a consensus path silently
  breaks it.
- **Jit discipline** — the batch and streaming throughput numbers depend
  on zero steady-state recompiles, no host syncs inside stage functions,
  and correct ``donate_argnums`` use (a donated buffer must never be read
  again).
- **Thread safety** — the background archive pack worker (store.archive)
  shares the spill queue, row cache, and drain barriers with the ingest
  thread; every shared attribute must be declared and audited.

This package enforces all three mechanically:

- :mod:`tpu_swirld.analysis.lint` — an AST-based invariant linter with
  project-specific rules (:mod:`tpu_swirld.analysis.rules`), a fix-it
  message and a suppression syntax per rule.  Runs clean over the package
  as a tier-1 test, so every future PR inherits the gate.
- :mod:`tpu_swirld.analysis.jit_audit` — a static + runtime auditor of
  the jitted stage functions: host-sync calls inside jit bodies,
  steady-state recompiles (cross-checked against
  :func:`tpu_swirld.obs.compile_counts`), and abstract-value
  dtype/weak_type drift between calls of the same stage.
- :mod:`tpu_swirld.analysis.races` — a schedule-fuzzing race sanitizer:
  yield-injection points in the archive's queue/worker/barrier code, a
  lock-order graph (deadlock freedom = acyclicity), and an N-schedule
  fuzz asserting the archive blob-stream digest is bit-identical under
  every interleaving (the async==sync pin from the overlapped pipeline,
  now quantified over randomized schedules).

CLI::

    python -m tpu_swirld.analysis lint tpu_swirld/
    python -m tpu_swirld.analysis jit-audit
    python -m tpu_swirld.analysis races --schedules 32
"""

from tpu_swirld.analysis.lint import (  # noqa: F401
    Finding,
    check_source,
    lint_paths,
    lint_summary,
)

__all__ = [
    "Finding",
    "check_source",
    "lint_paths",
    "lint_summary",
    "scale_audit",
    "scale_audit_stamp",
]


def __getattr__(name):
    # lazy: the flow package pulls in jax; plain lint use must not
    if name in ("scale_audit", "scale_audit_stamp"):
        from tpu_swirld.analysis.flow import audit

        return getattr(audit, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
