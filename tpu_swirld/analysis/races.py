"""Schedule-fuzzing race sanitizer for the archive's worker protocol.

The background pack worker (:mod:`tpu_swirld.store.archive`) shares a
bounded spill queue, a blob list, a byte counter, and an LRU row cache
with the client thread; correctness rests on the drain-barrier protocol,
not on per-attribute locks.  A protocol bug would surface as a
schedule-dependent blob stream — so the sanitizer *quantifies* the
async==sync pin over randomized schedules:

- **Yield injection** — :class:`Injector` sleeps a few microseconds with
  seeded probability at the tagged points compiled into the archive
  (``archive.enqueue``, ``archive.worker.item``, ``archive.drain``,
  ``archive.append``, ``archive.cache.miss``), perturbing the
  client/worker interleaving differently per seed.
- **Lock-order graph** — :class:`SanitizedArchive` swaps the spill
  queue's internal mutex for a :class:`TrackedLock`; every acquire
  records held→acquired edges, and a cycle in the graph is a potential
  deadlock (freedom = acyclicity).
- **Digest equality** — :func:`run_archive_schedules` runs a seeded
  spill/fetch/checkpoint workload under N schedules and asserts the
  BLAKE2b blob-stream digest is bit-identical across all of them *and*
  equal to a fully synchronous (``async_spill=False``) reference run.

:func:`run_schedules` is the generic harness: any callable that returns
a comparable result is run under N schedules and reported as
deterministic or not — the test suite uses it to prove the sanitizer
catches a deliberately-seeded lost update.

CLI: ``python -m tpu_swirld.analysis races --schedules 32``.
"""

from __future__ import annotations

import contextlib
import os
import random
import tempfile
import threading
import time
import queue
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpu_swirld.store.archive import SlabArchive

# ------------------------------------------------------------- injection


class Injector:
    """Seeded yield injector: ``point(tag)`` sleeps up to ``max_sleep``
    seconds with probability ``p``.  One instance = one schedule; the
    same seed replays the same injection decisions (modulo OS
    scheduling, which the sleeps are there to perturb)."""

    def __init__(self, seed: int, p: float = 0.25,
                 max_sleep: float = 5e-5):
        self._rng = random.Random(seed)
        self._mu = threading.Lock()
        self.p = p
        self.max_sleep = max_sleep
        self.fired = 0
        self.points = 0

    def point(self, tag: str) -> None:
        with self._mu:
            self.points += 1
            r = self._rng.random()
            fire = r < self.p
            if fire:
                self.fired += 1
                delay = r / self.p * self.max_sleep
        if fire:
            time.sleep(delay)


#: ambient injector for fixture code (see :func:`yield_point`)
_active: Optional[Injector] = None


def yield_point(tag: str) -> None:
    """Fixture-side injection point: racy test classes call this where a
    real implementation would have a preemption window."""
    a = _active
    if a is not None:
        a.point(tag)


@contextlib.contextmanager
def injection(inj: Injector):
    """Install ``inj`` as the ambient injector for both fixture
    ``yield_point`` calls and the archive's compiled-in points."""
    global _active
    from tpu_swirld.store import archive as archive_mod

    prev = _active
    _active = inj
    archive_mod.set_injector(inj)
    try:
        yield inj
    finally:
        _active = prev
        archive_mod.set_injector(prev)


# ------------------------------------------------------- lock-order graph


class LockOrderGraph:
    """Held→acquired edges recorded at every tracked acquire; a cycle is
    a potential deadlock (two threads can reach the opposite-order
    acquires concurrently)."""

    def __init__(self):
        self.edges: set = set()
        self._tl = threading.local()
        self._mu = threading.Lock()

    def _held(self) -> List[str]:
        stack = getattr(self._tl, "stack", None)
        if stack is None:
            stack = self._tl.stack = []
        return stack

    def on_acquire(self, name: str) -> None:
        held = self._held()
        if held:
            with self._mu:
                for h in held:
                    if h != name:
                        self.edges.add((h, name))
        held.append(name)

    def on_release(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break

    def cycle(self) -> Optional[List[str]]:
        """A lock-name cycle if one exists, else None (acyclic)."""
        with self._mu:
            adj: Dict[str, List[str]] = {}
            for a, b in sorted(self.edges):
                adj.setdefault(a, []).append(b)
        state: Dict[str, int] = {}   # 1 = on stack, 2 = done
        path: List[str] = []

        def dfs(v: str) -> Optional[List[str]]:
            state[v] = 1
            path.append(v)
            for w in adj.get(v, ()):
                if state.get(w) == 1:
                    return path[path.index(w):] + [w]
                if state.get(w) is None:
                    c = dfs(w)
                    if c:
                        return c
            path.pop()
            state[v] = 2
            return None

        for v in sorted(adj):
            if state.get(v) is None:
                c = dfs(v)
                if c:
                    return c
        return None


class TrackedLock:
    """``threading.Lock`` wrapper feeding a :class:`LockOrderGraph`;
    usable as the lock of a ``threading.Condition`` (the default
    release/re-acquire path goes through :meth:`acquire` /
    :meth:`release`, so condition waits are tracked too)."""

    def __init__(self, name: str, graph: LockOrderGraph):
        self.name = name
        self.graph = graph
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self.graph.on_acquire(self.name)
        return ok

    def release(self) -> None:
        self.graph.on_release(self.name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


class _TrackedQueue(queue.Queue):
    """``queue.Queue`` whose internal mutex is a :class:`TrackedLock`;
    the three condition variables are rebuilt on it so waiter wakeups
    keep working."""

    def __init__(self, maxsize: int, graph: LockOrderGraph,
                 name: str = "archive.q"):
        super().__init__(maxsize)
        self.mutex = TrackedLock(name + ".mutex", graph)
        self.not_empty = threading.Condition(self.mutex)
        self.not_full = threading.Condition(self.mutex)
        self.all_tasks_done = threading.Condition(self.mutex)


class SanitizedArchive(SlabArchive):
    """SlabArchive whose spill queue participates in the lock-order
    graph (via the ``_make_queue`` seam)."""

    def __init__(self, *args, graph: Optional[LockOrderGraph] = None,
                 **kwargs):
        self._graph = graph if graph is not None else LockOrderGraph()
        super().__init__(*args, **kwargs)

    def _make_queue(self, maxsize: int) -> queue.Queue:
        return _TrackedQueue(maxsize, self._graph)


# --------------------------------------------------------------- harness


def run_schedules(
    fn: Callable[[int], Any],
    n_schedules: int = 8,
    seed: int = 0,
) -> Dict[str, Any]:
    """Run ``fn(schedule_index)`` under ``n_schedules`` seeded injection
    schedules; report whether every schedule produced the same result.
    A schedule-dependent result is a race made visible."""
    results: List[Any] = []
    for i in range(n_schedules):
        inj = Injector(seed=seed * 1009 + i)
        with injection(inj):
            results.append(fn(i))
    distinct = sorted({repr(r) for r in results})
    return {
        "schedules": n_schedules,
        "results": results,
        "distinct": len(distinct),
        "deterministic": len(distinct) == 1,
    }


def _closure_matrix(n: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """A chain-valid ancestry closure: row ``e`` = self ∪ anc(e-1) ∪
    anc(p2) for a seeded random ``p2 < e``.  Shaped exactly like the
    rows the streaming driver retires, so the archive's parent-prefix
    reconstruction is exercised for real."""
    rng = np.random.default_rng(seed)
    F = np.zeros((n, n), dtype=bool)
    parents = np.full((n, 2), -1, dtype=np.int32)
    for e in range(n):
        F[e, e] = True
        if e:
            F[e] |= F[e - 1]
            parents[e, 0] = e - 1
            p2 = int(rng.integers(0, e))
            F[e] |= F[p2]
            parents[e, 1] = p2
    return F, parents


def _archive_workload(
    arch: SlabArchive, F: np.ndarray, parents: np.ndarray,
    ws: int, tmpdir: str, batch: int = 8,
) -> str:
    """One seeded client sequence of spill / fetch / prefetch / digest /
    checkpoint against ``arch`` (the concurrency comes from the archive's
    own pack worker; the injector perturbs the interleaving).  Returns
    the final blob-stream digest; asserts every fetch matches ``F``."""
    rng = random.Random(ws)
    n = F.shape[0]
    mid_path = os.path.join(tmpdir, f"mid-{ws}.npz")
    for lo in range(0, n, batch):
        d = min(batch, n - lo)
        arch.spill(lo, parents[lo : lo + d], F[lo : lo + d, lo : lo + d])
        r = rng.random()
        if r < 0.35 and arch.n_rows > 1:
            f_lo = rng.randrange(0, arch.n_rows - 1)
            f_hi = rng.randrange(f_lo + 1, arch.n_rows + 1)
            c_hi = rng.randrange(1, f_hi + 1)
            got = arch.fetch(f_lo, f_hi, 0, c_hi)
            want = F[f_lo:f_hi, :c_hi]
            if not np.array_equal(got, want):
                raise AssertionError(
                    f"schedule {ws}: fetch [{f_lo},{f_hi})x[0,{c_hi}) "
                    "diverged from the reference closure"
                )
        elif r < 0.5:
            arch.prefetch(max(0, arch.n_rows - 16), arch.n_rows)
        elif r < 0.6:
            arch.digest()
        if lo == (n // batch // 2) * batch:
            arch.save(mid_path)
            if SlabArchive.load(mid_path).digest() != arch.digest():
                raise AssertionError(
                    f"schedule {ws}: mid-run checkpoint digest mismatch"
                )
    dig = arch.digest()
    arch.close()
    return dig


def run_archive_schedules(
    n_schedules: int = 32,
    seed: int = 0,
    rows: int = 96,
    queue_depth: int = 2,
) -> Dict[str, Any]:
    """The acceptance-criteria fuzz: ``n_schedules`` seeded schedules of
    concurrent ingest/spill/fetch/checkpoint must produce bit-identical
    archive digests, match a fully synchronous reference run (the PR-6
    async==sync pin), and leave the lock-order graph acyclic."""
    F, parents = _closure_matrix(rows, seed=seed + 7)
    graph = LockOrderGraph()
    digests: List[str] = []
    with tempfile.TemporaryDirectory() as tmpdir:
        # synchronous reference: no worker, no injection
        sync_arch = SlabArchive(async_spill=False)
        sync_digest = _archive_workload(
            sync_arch, F, parents, ws=seed, tmpdir=tmpdir
        )
        for i in range(n_schedules):
            inj = Injector(seed=seed * 1009 + i)
            arch = SanitizedArchive(
                async_spill=True, queue_depth=queue_depth, graph=graph,
            )
            with injection(inj):
                digests.append(_archive_workload(
                    arch, F, parents, ws=seed, tmpdir=tmpdir
                ))
    cyc = graph.cycle()
    identical = len(set(digests)) == 1
    matches_sync = identical and digests and digests[0] == sync_digest
    return {
        "schedules": n_schedules,
        "digest": digests[0] if digests else None,
        "digests_identical": identical,
        "sync_digest": sync_digest,
        "matches_sync": bool(matches_sync),
        "lock_edges": sorted(graph.edges),
        "acyclic": cyc is None,
        "cycle": cyc,
        "ok": bool(identical and matches_sync and cyc is None),
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python -m tpu_swirld.analysis races",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("--schedules", type=int, default=32)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--rows", type=int, default=96)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    report = run_archive_schedules(
        n_schedules=args.schedules, seed=args.seed, rows=args.rows
    )
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(
            f"{report['schedules']} schedules: digests "
            f"{'identical' if report['digests_identical'] else 'DIVERGED'}, "
            f"sync match {report['matches_sync']}, "
            f"lock graph {'acyclic' if report['acyclic'] else 'CYCLIC'}"
        )
        print("OK" if report["ok"] else "FAIL")
    return 0 if report["ok"] else 1
