"""SW004 dtype-discipline: explicit dtypes in kernel/slab code.

NumPy allocation defaults are platform-flavored (``np.arange`` without a
dtype is int64 on linux); jnp defaults to int32/float32 with x64
disabled.  A slab or index array that silently lands in int64 doubles
HBM traffic, breaks the int32 kernels' shape buckets, and — worst —
recompiles every stage the array feeds.  The rule forbids implicit
dtypes on array allocations in ``tpu/``, ``store/``, and
``parallel.py``, plus builtin-``int``/``float`` as dtype arguments
(their width is platform-defined).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from tpu_swirld.analysis.lint import FileContext, Finding
from tpu_swirld.analysis.rules import Rule

#: allocator -> number of positional args at which dtype is covered
#: (np.zeros(shape, dtype) -> 2 positionals mean dtype was passed)
_NP_ALLOCATORS = {
    "zeros": 2, "ones": 2, "empty": 2, "arange": 4, "full": 3,
}
#: jnp.arange defaults to int32 (x64 off) so it is exempt; the others
#: still deserve an explicit dtype for reviewability + weak_type control
_JNP_ALLOCATORS = {"zeros": 2, "ones": 2, "empty": 2, "full": 3, "eye": 3}

#: builtin ``bool`` is exempt: as a dtype it IS np.bool_ (1 byte
#: everywhere); int/float are C-long/double and platform-flavored
_BUILTIN_DTYPES = {"int", "float"}


class DtypeRule(Rule):
    id = "SW004"
    name = "dtype-discipline"
    describe = (
        "kernel/slab allocations must pin an explicit dtype "
        "(np defaults promote to int64/float64 and break the int32 "
        "shape buckets); builtin int/float dtypes are platform-width"
    )
    scope = ("tpu/", "store/", "parallel.py")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) and isinstance(
                fn.value, ast.Name
            ):
                mod, attr = fn.value.id, fn.attr
                table = None
                if mod in ("np", "numpy"):
                    table = _NP_ALLOCATORS
                elif mod == "jnp":
                    table = _JNP_ALLOCATORS
                if table is not None and attr in table:
                    has_dtype_kw = any(
                        kw.arg == "dtype" for kw in node.keywords
                    )
                    if not has_dtype_kw and len(node.args) < table[attr]:
                        default = (
                            "int64" if attr == "arange" else
                            "float64 (np) / weak float32 (jnp)"
                        )
                        out.append(self.finding(
                            ctx, node,
                            f"{mod}.{attr}(...) without an explicit dtype "
                            f"defaults to {default} — doubles slab bytes "
                            "and recompiles int32 stages; fix: pass "
                            "dtype=np.int32 / np.bool_ / the slab's "
                            "matmul dtype explicitly",
                        ))
                # .astype(int) and friends
                if attr == "astype" and node.args:
                    a = node.args[0]
                    if isinstance(a, ast.Name) and a.id in _BUILTIN_DTYPES:
                        out.append(self.finding(
                            ctx, node,
                            f".astype({a.id}) uses the platform-width "
                            "builtin; fix: name the width "
                            "(np.int32 / np.float32 / np.bool_)",
                        ))
            # dtype=int / dtype=float keyword on any call
            for kw in node.keywords:
                if (
                    kw.arg == "dtype"
                    and isinstance(kw.value, ast.Name)
                    and kw.value.id in _BUILTIN_DTYPES
                ):
                    out.append(self.finding(
                        ctx, kw.value,
                        f"dtype={kw.value.id} is the platform-width "
                        "builtin; fix: name the width "
                        "(np.int32 / np.float32 / np.bool_)",
                    ))
        return out
