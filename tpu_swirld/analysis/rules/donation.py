"""SW005 donation-discipline: never read a buffer after donating it.

``donate_argnums`` hands the buffer's memory to XLA; the Python-side
array is left pointing at deleted device memory, and the next read
raises (or, under some backends, silently aliases).  The package's
convention is to rebind the result to the same name in the same
statement (``self._anc_d = obs.stage_call("x", stage, self._anc_d,
...)``), which this rule verifies mechanically.

The rule tracks three call shapes against the cross-file donation index
built by :class:`tpu_swirld.analysis.lint.PackageIndex`:

- direct: ``update_block_stage(buf, ...)`` where the stage was defined
  with ``donate_argnums``;
- wrapped: ``obs.stage_call("name", stage, buf, ...)`` — donated
  positions shift by +2 for the label and function arguments; the fused
  variant ``obs.stage_call_fused("name", k, stage, buf, ...)`` shifts
  by +3 (label, fused-chunk count, function);
- factory: ``make_extend_visibility_stage(kern)(buf, ...)`` — the
  factory's inner jitted def declares the donation.

Within each function scope, statements are walked linearly: a load of a
donated name (or dotted ``self.attr`` chain) after the donating call and
before a rebinding store is a finding.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from tpu_swirld.analysis.lint import FileContext, Finding
from tpu_swirld.analysis.rules import Rule


def _key(expr) -> Optional[str]:
    """Flatten ``Name`` / dotted ``Attribute`` chains to a tracking key
    (``buf``, ``self._anc_d``); anything else is untrackable."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = _key(expr.value)
        if base is not None:
            return base + "." + expr.attr
    return None


class DonationRule(Rule):
    id = "SW005"
    name = "donation-discipline"
    describe = (
        "a buffer passed at a donate_argnums position is dead after the "
        "call; rebind the result to the same name in the same statement "
        "and never read the old binding"
    )
    scope = ()

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_fn(ctx, node, out)
        return out

    # -- call-site resolution -------------------------------------------

    def _donated_arg_keys(self, call: ast.Call) -> List[Tuple[str, str]]:
        """``(key, stage_name)`` for each trackable donated argument of
        a call, or [] if the call donates nothing we can resolve."""
        idx = ctx_index = self._index
        fn = call.func
        positions: Tuple[int, ...] = ()
        stage = ""
        args = call.args
        if isinstance(fn, ast.Name) and fn.id in idx.donations:
            positions, stage = idx.donations[fn.id], fn.id
        elif (
            isinstance(fn, ast.Call)
            and isinstance(fn.func, ast.Name)
            and fn.func.id in idx.donation_factories
        ):
            positions = idx.donation_factories[fn.func.id]
            stage = fn.func.id
        elif (
            (isinstance(fn, ast.Attribute) and fn.attr == "stage_call")
            or (isinstance(fn, ast.Name) and fn.id == "stage_call")
        ) and len(args) >= 2:
            inner = args[1]
            if isinstance(inner, ast.Name):
                if inner.id in idx.donations:
                    positions = tuple(
                        p + 2 for p in idx.donations[inner.id]
                    )
                    stage = inner.id
            elif (
                isinstance(inner, ast.Call)
                and isinstance(inner.func, ast.Name)
                and inner.func.id in ctx_index.donation_factories
            ):
                positions = tuple(
                    p + 2 for p in idx.donation_factories[inner.func.id]
                )
                stage = inner.func.id
        elif (
            (isinstance(fn, ast.Attribute) and fn.attr == "stage_call_fused")
            or (isinstance(fn, ast.Name) and fn.id == "stage_call_fused")
        ) and len(args) >= 3:
            # fused wrapper: (label, fused_chunks, fn, *args) — donated
            # positions shift by +3.  This covers the scan-carry donation
            # shape: rounds_span_stage donates its carry slabs, and the
            # fixpoint caller must re-upload rather than reuse them.
            inner = args[2]
            if isinstance(inner, ast.Name):
                if inner.id in idx.donations:
                    positions = tuple(
                        p + 3 for p in idx.donations[inner.id]
                    )
                    stage = inner.id
            elif (
                isinstance(inner, ast.Call)
                and isinstance(inner.func, ast.Name)
                and inner.func.id in ctx_index.donation_factories
            ):
                positions = tuple(
                    p + 3 for p in idx.donation_factories[inner.func.id]
                )
                stage = inner.func.id
        keys = []
        for p in positions:
            if p < len(args):
                k = _key(args[p])
                if k is not None:
                    keys.append((k, stage))
        return keys

    # -- linear scope walk ----------------------------------------------

    def _check_fn(self, ctx, fn, out) -> None:
        self._index = ctx.index
        donated: Dict[str, str] = {}   # key -> donating stage name
        self._stmts(ctx, fn.body, donated, out)

    def _stmts(self, ctx, body, donated, out) -> None:
        for st in body:
            self._stmt(ctx, st, donated, out)

    def _stmt(self, ctx, st, donated, out) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return   # walked as its own scope by check()
        if isinstance(st, ast.Assign):
            self._expr(ctx, st.value, donated, out)
            for t in st.targets:
                self._clear_target(t, donated)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._expr(ctx, st.value, donated, out)
            self._clear_target(st.target, donated)
        elif isinstance(st, ast.AugAssign):
            self._expr(ctx, st.value, donated, out)
            k = _key(st.target)
            if k is not None and k in donated:
                out.append(self.finding(
                    ctx, st.target,
                    f"'{k}' was donated to {donated[k]}() and is "
                    "augmented here — the buffer is already dead; fix: "
                    "rebind the stage's return value instead",
                ))
                donated.pop(k, None)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._expr(ctx, st.iter, donated, out)
            self._clear_target(st.target, donated)
            self._stmts(ctx, st.body, donated, out)
            self._stmts(ctx, st.orelse, donated, out)
        elif isinstance(st, (ast.If, ast.While)):
            self._expr(ctx, st.test, donated, out)
            self._stmts(ctx, st.body, donated, out)
            self._stmts(ctx, st.orelse, donated, out)
        elif isinstance(st, ast.Try):
            self._stmts(ctx, st.body, donated, out)
            for h in st.handlers:
                self._stmts(ctx, h.body, donated, out)
            self._stmts(ctx, st.orelse, donated, out)
            self._stmts(ctx, st.finalbody, donated, out)
        elif isinstance(st, ast.With):
            for item in st.items:
                self._expr(ctx, item.context_expr, donated, out)
            self._stmts(ctx, st.body, donated, out)
        elif isinstance(st, ast.Return):
            if st.value is not None:
                self._expr(ctx, st.value, donated, out)
        elif isinstance(st, ast.Expr):
            self._expr(ctx, st.value, donated, out)
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                k = _key(t)
                if k is not None:
                    donated.pop(k, None)

    def _clear_target(self, target, donated) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._clear_target(e, donated)
            return
        k = _key(target)
        if k is not None:
            # a store to self.x also revives self.x.anything
            for d in [d for d in donated if d == k or d.startswith(k + ".")]:
                donated.pop(d, None)

    def _expr(self, ctx, expr, donated, out) -> None:
        # 1) every trackable load checked against the donated set
        for node in ast.walk(expr):
            if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, "ctx", None), ast.Load
            ):
                k = _key(node)
                if k is not None and k in donated:
                    out.append(self.finding(
                        ctx, node,
                        f"'{k}' is read after being donated to "
                        f"{donated[k]}() — donate_argnums freed that "
                        "buffer; fix: use the stage's return value, or "
                        "copy before the donating call",
                    ))
        # 2) then record fresh donations made by calls in this expression
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                for k, stage in self._donated_arg_keys(node):
                    donated[k] = stage
