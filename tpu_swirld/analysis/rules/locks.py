"""SW006 lock-discipline: declared guarded-attribute sets for workers.

A class that starts a ``threading.Thread(target=self.X)`` shares every
attribute the worker closure touches with the client thread.  The
package's convention (``SlabArchive.GUARDED_ATTRS``) is an explicit
class-level ``frozenset`` naming that shared mutable state, so a review
of the queue/barrier protocol has a definitive list to audit and a new
attribute cannot silently join the shared set.

The rule computes the worker's transitive closure over self-method
calls, collects the ``self.attr`` accesses inside it, and requires every
*mutable* one (stored by the worker, or stored anywhere outside
``__init__``) to appear in ``GUARDED_ATTRS``.  Attributes only ever
assigned in ``__init__`` are immutable-after-start and exempt.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from tpu_swirld.analysis.lint import FileContext, Finding
from tpu_swirld.analysis.rules import Rule


def _is_thread_ctor(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr == "Thread":
        return True
    if isinstance(fn, ast.Name) and fn.id == "Thread":
        return True
    return False


def _thread_target_method(call: ast.Call) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == "target":
            v = kw.value
            if (
                isinstance(v, ast.Attribute)
                and isinstance(v.value, ast.Name)
                and v.value.id == "self"
            ):
                return v.attr
    return None


def _declared_guarded(cls: ast.ClassDef) -> Optional[Set[str]]:
    """The class-level ``GUARDED_ATTRS`` declaration, or None."""
    for st in cls.body:
        targets = []
        value = None
        if isinstance(st, ast.Assign):
            targets, value = st.targets, st.value
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            targets, value = [st.target], st.value
        if not any(
            isinstance(t, ast.Name) and t.id == "GUARDED_ATTRS"
            for t in targets
        ):
            continue
        names: Set[str] = set()
        for node in ast.walk(value):
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                names.add(node.value)
        return names
    return None


#: method calls that mutate their receiver — ``self.X.append(...)``
#: counts as a store of ``X``
_MUTATORS = {
    "append", "extend", "insert", "pop", "popitem", "clear", "update",
    "add", "remove", "discard", "setdefault", "move_to_end",
    "put", "put_nowait", "get", "get_nowait", "task_done",
}


def _self_attr(node) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _AttrUse(ast.NodeVisitor):
    """self.attr loads/stores and self-method references in one method.
    Stores include plain/aug assignment, ``self.X[...] = ...`` subscript
    stores, and mutator method calls (``self.X.append(...)``)."""

    def __init__(self):
        self.loads: Dict[str, ast.AST] = {}
        self.stores: Set[str] = set()
        self.method_refs: Set[str] = set()

    def visit_Attribute(self, node):
        if _self_attr(node) is not None:
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                self.stores.add(node.attr)
            else:
                self.loads.setdefault(node.attr, node)
                self.method_refs.add(node.attr)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        a = _self_attr(node.target)
        if a is not None:
            self.stores.add(a)
            self.loads.setdefault(a, node.target)
        self.generic_visit(node)

    def visit_Subscript(self, node):
        a = _self_attr(node.value)
        if a is not None and isinstance(node.ctx, (ast.Store, ast.Del)):
            self.stores.add(a)
            self.loads.setdefault(a, node)
        self.generic_visit(node)

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS:
            a = _self_attr(fn.value)
            if a is not None:
                self.stores.add(a)
                self.loads.setdefault(a, node)
        self.generic_visit(node)


class LockDisciplineRule(Rule):
    id = "SW006"
    name = "lock-discipline"
    describe = (
        "every mutable attribute a background worker thread touches must "
        "appear in the owning class's GUARDED_ATTRS frozenset"
    )
    scope = ()

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef):
                self._check_class(ctx, cls, out)
        return out

    def _check_class(self, ctx, cls, out) -> None:
        methods: Dict[str, ast.FunctionDef] = {
            st.name: st
            for st in cls.body
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # worker entry points + the Thread() calls that start them
        targets: List = []
        for m in methods.values():
            for node in ast.walk(m):
                if isinstance(node, ast.Call) and _is_thread_ctor(node):
                    t = _thread_target_method(node)
                    if t is not None and t in methods:
                        targets.append((t, node))
        if not targets:
            return
        uses = {name: self._uses(m) for name, m in methods.items()}
        mutated_outside_init: Set[str] = set()
        for name, u in uses.items():
            if name != "__init__":
                mutated_outside_init |= u.stores
        # transitive closure of self-calls from the worker entry points
        closure: Set[str] = set()
        frontier = [t for t, _ in targets]
        while frontier:
            m = frontier.pop()
            if m in closure:
                continue
            closure.add(m)
            frontier.extend(
                r for r in uses[m].method_refs
                if r in methods and r not in closure
            )
        worker_loads: Dict[str, ast.AST] = {}
        worker_stores: Set[str] = set()
        for m in closure:
            for a, node in uses[m].loads.items():
                if a not in methods:
                    worker_loads.setdefault(a, node)
            worker_stores |= {a for a in uses[m].stores if a not in methods}
        required = sorted(
            set(worker_loads) & (worker_stores | mutated_outside_init)
            | worker_stores
        )
        if not required:
            return
        declared = _declared_guarded(cls)
        if declared is None:
            _, thread_call = targets[0]
            out.append(self.finding(
                ctx, thread_call,
                f"class {cls.name} starts a worker thread but declares "
                "no GUARDED_ATTRS; fix: add a class-level frozenset "
                "naming the shared mutable attributes "
                f"({', '.join(required)})",
            ))
            return
        for a in required:
            if a not in declared:
                node = worker_loads.get(a) or targets[0][1]
                out.append(self.finding(
                    ctx, node,
                    f"worker thread of {cls.name} touches mutable "
                    f"attribute '{a}' which is missing from "
                    "GUARDED_ATTRS; fix: add it to the declaration and "
                    "audit its synchronization",
                ))

    @staticmethod
    def _uses(m) -> _AttrUse:
        u = _AttrUse()
        for st in m.body:
            u.visit(st)
        return u
