"""Rule catalog for the invariant linter.

Each rule is a small AST pass with a fixed id (``SWNNN``), a slug, a
module scope (empty = whole package), and a fix-it message baked into
every finding.  Suppress a finding with ``# swirld-lint: disable=<id>``
on the flagged line (see :mod:`tpu_swirld.analysis.lint`).

Catalog:

- **SW001 unseeded-rng** — no global-state RNG (``random.*``,
  ``np.random.*``) anywhere in the package; randomness must flow from a
  seeded ``random.Random`` / ``np.random.default_rng(seed)`` instance.
- **SW002 unordered-iter** — no hash-order ``set`` iteration in the
  consensus-critical modules (``oracle/``, ``store/streaming.py``,
  ``tpu/pipeline.py``, ``chaos.py``, ``membership/``) without an
  explicit ``sorted()``.
- **SW003 wall-clock** — no ``time.time`` / ``time.sleep`` /
  ``datetime.now`` in the logical-time transport/retry layer.  Inside
  ``net/`` (the socket deployment edge, which legitimately needs real
  deadlines) the rule still applies but accepts *justified* line
  suppressions only — ``disable=SW003 -- <why>`` with a non-empty note,
  mirroring the SW008 flow-audit semantics; bare disables and
  ``disable-file`` do not count.
- **SW004 dtype-discipline** — kernel/slab allocations (``tpu/``,
  ``store/``, ``parallel.py``) must pin an explicit dtype; NumPy's
  implicit int64/float64 promotion and builtin-``int`` dtypes are
  forbidden.
- **SW005 donation-discipline** — a buffer passed at a
  ``donate_argnums`` position (directly, through ``obs.stage_call``, or
  through a ``make_*`` stage factory) must not be read afterwards in the
  same scope until rebound.
- **SW006 lock-discipline** — every ``self`` attribute a background
  worker thread touches must appear in the owning class's declared
  ``GUARDED_ATTRS`` frozenset.
- **SW007 load-bearing-assert** — no ``assert`` statements in the
  production modules (``oracle/``, ``store/``, ``tpu/``,
  ``transport.py``, ``parallel.py``, ``packing.py``,
  ``membership/``): asserts vanish
  under ``python -O``; safety checks must be explicit raises (with a
  counter where useful).

The SW008–SW011 ids belong to the scale-envelope flow audit
(:mod:`tpu_swirld.analysis.flow`) — they are emitted by the jaxpr-level
abstract interpreter rather than an AST pass, but share this id space,
finding format, and suppression syntax (with a *required* ``--
<justification>`` tail):

- **SW008 overflow-reachable** — an integer result's proven value
  interval escapes its dtype at the declared scale envelope.
- **SW009 unproven-bounds** — a gather/scatter/``dynamic_slice`` index
  interval is not provably inside the operand extent (XLA would clamp
  or drop silently).
- **SW010 lossy-narrowing** — a ``convert_element_type`` narrows to a
  dtype that cannot represent the operand's proven interval.
- **SW011 sentinel-collision** — a live value range can collide with a
  padding sentinel (e.g. ``INT32_MAX`` timestamps in the order stage).
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

from tpu_swirld.analysis.lint import FileContext, Finding


class Rule:
    """Base class: subclasses set ``id``/``name``/``scope``/``describe``
    and implement :meth:`check`."""

    id: str = "SW000"
    name: str = "base"
    describe: str = ""
    #: module-path prefixes this rule applies to; empty = every module
    scope: Tuple[str, ...] = ()
    #: module-path prefixes where only a *justified* line suppression
    #: (``# swirld-lint: disable=<id> -- <why>``) silences a finding —
    #: bare disables and ``disable-file`` do not (the SW008 flow-audit
    #: semantics, opt-in per rule/scope)
    note_scope: Tuple[str, ...] = ()

    def applies(self, module_path: str) -> bool:
        if not self.scope:
            return True
        return any(
            module_path == s or module_path.startswith(s)
            for s in self.scope
        )

    def requires_note(self, module_path: str) -> bool:
        return any(
            module_path == s or module_path.startswith(s)
            for s in self.note_scope
        )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node, message: str) -> Finding:
        return Finding(
            self.id, self.name, ctx.path,
            getattr(node, "lineno", 0), getattr(node, "col_offset", 0),
            message,
        )


def all_rules() -> List[Rule]:
    from tpu_swirld.analysis.rules.determinism import (
        UnorderedIterRule, UnseededRngRule, WallClockRule,
    )
    from tpu_swirld.analysis.rules.asserts import LoadBearingAssertRule
    from tpu_swirld.analysis.rules.donation import DonationRule
    from tpu_swirld.analysis.rules.dtype import DtypeRule
    from tpu_swirld.analysis.rules.locks import LockDisciplineRule

    return [
        UnseededRngRule(),
        UnorderedIterRule(),
        WallClockRule(),
        DtypeRule(),
        DonationRule(),
        LockDisciplineRule(),
        LoadBearingAssertRule(),
    ]
