"""SW007: load-bearing ``assert`` statements in production modules.

``assert`` statements are compiled out under ``python -O`` — a safety
check written as an assert silently vanishes in optimized deployments,
turning a loud shape/invariant failure into corrupt downstream state.
In the production consensus, store, kernel, and transport modules every
assert IS load-bearing (there is no "debug-only" tier there), so the
rule flags them all: guards belong in explicit ``if not cond: raise``
form, with a counter where observability helps (the pattern lives in
``tpu_swirld.tpu.pipeline.ShapeContractError`` /
``shape_guard_trips``).

Tests and benches keep their asserts (pytest rewrites them; benches are
never run under ``-O``); the scope below covers the modules that ship.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from tpu_swirld.analysis.lint import FileContext, Finding
from tpu_swirld.analysis.rules import Rule


class LoadBearingAssertRule(Rule):
    id = "SW007"
    name = "load-bearing-assert"
    describe = (
        "assert statements vanish under python -O; production safety "
        "checks must be explicit raises (with a counter where useful — "
        "see tpu.pipeline.ShapeContractError) that survive optimization"
    )
    scope = (
        "oracle/", "store/", "tpu/", "transport.py", "parallel.py",
        "packing.py", "membership/",
    )

    _FIX = (
        "is compiled out under python -O, so this guard silently "
        "disappears in optimized deployments; fix: explicit "
        "`if not <cond>: raise <Error>(...)` (count the trips where "
        "observability helps, like tpu.pipeline.shape_guard_trips)"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assert):
                out.append(self.finding(
                    ctx, node, "assert statement " + self._FIX,
                ))
        return out
