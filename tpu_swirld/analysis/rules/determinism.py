"""Determinism rules: unseeded RNG, unordered-set iteration, wall-clock.

Consensus safety here is "decided prefixes bit-identical across every
node, engine, and replay".  These three rules pin the ways plain Python
quietly breaks that:

- module-level RNG draws from interpreter-global state no replay controls;
- ``set`` iteration order is hash-randomized per process
  (PYTHONHASHSEED) for ``bytes``/``str`` elements — two nodes walking the
  same set can diverge;
- wall-clock reads differ across nodes and replays, so nothing in the
  logical-time transport/retry layer may consult them.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from tpu_swirld.analysis.lint import FileContext, Finding
from tpu_swirld.analysis.rules import Rule

# ---------------------------------------------------------------- SW001

#: np.random attributes that are seeded-constructor entry points (fine
#: when called WITH a seed argument)
_NP_SEEDED = {"default_rng", "SeedSequence", "Generator", "RandomState"}


class UnseededRngRule(Rule):
    id = "SW001"
    name = "unseeded-rng"
    describe = (
        "global-state RNG (random.*, np.random.*) is unseeded shared "
        "state; thread a seeded random.Random(seed) / "
        "np.random.default_rng(seed) instance through instead"
    )
    scope = ()   # whole package

    _FIX = (
        "draws from interpreter-global RNG state — any consensus or sim "
        "path using it is unreplayable; fix: accept a seeded "
        "random.Random / np.random.default_rng(seed) instance"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            # random.X(...) for module-level X (not the Random class)
            if (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "random"
            ):
                if fn.attr in ("Random", "SystemRandom"):
                    if fn.attr == "Random" and not node.args:
                        out.append(self.finding(
                            ctx, node,
                            "random.Random() without a seed; " + self._FIX,
                        ))
                    continue
                out.append(self.finding(
                    ctx, node, f"random.{fn.attr}() " + self._FIX,
                ))
            # np.random.X(...) / numpy.random.X(...)
            elif (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Attribute)
                and fn.value.attr == "random"
                and isinstance(fn.value.value, ast.Name)
                and fn.value.value.id in ("np", "numpy")
            ):
                if fn.attr in _NP_SEEDED:
                    if not node.args and not node.keywords:
                        out.append(self.finding(
                            ctx, node,
                            f"np.random.{fn.attr}() without a seed; "
                            + self._FIX,
                        ))
                    continue
                out.append(self.finding(
                    ctx, node, f"np.random.{fn.attr}() " + self._FIX,
                ))
        return out


# ---------------------------------------------------------------- SW002

#: set-returning methods (attribute calls)
_SET_METHODS = {
    "union", "intersection", "difference", "symmetric_difference",
}
#: order-insensitive consumers a set may flow into un-sorted
_ORDER_FREE = {
    "sorted", "len", "min", "max", "sum", "any", "all", "set",
    "frozenset", "bool",
}
#: order-sensitive consumers of an iterable argument
_ORDER_SENSITIVE = {"list", "tuple", "enumerate", "iter", "next"}


class _SetNames(ast.NodeVisitor):
    """Names/attributes inferred set-typed within one scope (conservative:
    any assignment from a set-producing expression marks the name)."""

    def __init__(self):
        self.names: Set[str] = set()
        self.attr_sets: Set[str] = set()        # self.X is a set
        self.attr_dict_of_set: Set[str] = set() # self.X[...] is a set

    def visit_Assign(self, node):
        if _is_set_producing(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.names.add(t.id)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        ann_kind = _annotation_kind(node.annotation)
        if isinstance(node.target, ast.Name):
            if ann_kind == "set" or (
                node.value is not None and _is_set_producing(node.value)
            ):
                self.names.add(node.target.id)
        elif (
            isinstance(node.target, ast.Attribute)
            and isinstance(node.target.value, ast.Name)
            and node.target.value.id == "self"
        ):
            if ann_kind == "set":
                self.attr_sets.add(node.target.attr)
            elif ann_kind == "dict_of_set":
                self.attr_dict_of_set.add(node.target.attr)
        self.generic_visit(node)


def _annotation_kind(ann) -> Optional[str]:
    """'set', 'dict_of_set', or None for a type annotation node."""
    if isinstance(ann, ast.Name) and ann.id in ("set", "frozenset"):
        return "set"
    if isinstance(ann, ast.Subscript):
        base = ann.value
        if isinstance(base, ast.Name):
            if base.id in ("Set", "FrozenSet"):
                return "set"
            if base.id in ("Dict", "dict"):
                sl = ann.slice
                if isinstance(sl, ast.Tuple) and len(sl.elts) == 2:
                    if _annotation_kind(sl.elts[1]) == "set":
                        return "dict_of_set"
    return None


def _is_set_producing(expr) -> bool:
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call):
        fn = expr.func
        if isinstance(fn, ast.Name) and fn.id in ("set", "frozenset"):
            return True
        if isinstance(fn, ast.Attribute) and fn.attr in _SET_METHODS:
            return True
    return False


class UnorderedIterRule(Rule):
    id = "SW002"
    name = "unordered-iter"
    describe = (
        "set iteration order is hash-randomized (PYTHONHASHSEED); in "
        "consensus-critical modules iterate sorted(the_set) or an "
        "ordered container"
    )
    scope = (
        "oracle/", "store/streaming.py", "tpu/pipeline.py", "chaos.py",
        "adversary.py", "obs/finality.py", "obs/flightrec.py",
        "obs/cluster_trace.py", "obs/profile.py",
        "net/proxy.py", "net/traffic.py", "soak.py",
        "membership/",
    )

    _FIX = (
        "iterates a set — order is hash-randomized per process, so two "
        "nodes (or a node and its replay) can walk it differently; fix: "
        "sorted(...) with a deterministic key, or keep an ordered "
        "container alongside the set"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        # class-attribute annotations are file-global facts
        ann = _SetNames()
        ann.visit(ctx.tree)
        scopes = [ctx.tree] + [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            names = _SetNames()
            for st in scope.body:
                names.visit(st)
            names.attr_sets |= ann.attr_sets
            names.attr_dict_of_set |= ann.attr_dict_of_set
            names.names |= {
                a.arg for a in getattr(
                    getattr(scope, "args", None), "args", []
                )
                if a.annotation is not None
                and _annotation_kind(a.annotation) == "set"
            }
            self._check_scope(ctx, scope, names, out)
        # dedupe (module scope nests function bodies)
        seen = set()
        uniq = []
        for f in out:
            key = (f.line, f.col, f.message)
            if key not in seen:
                seen.add(key)
                uniq.append(f)
        return uniq

    def _is_set(self, expr, names: _SetNames) -> bool:
        if _is_set_producing(expr):
            return True
        if isinstance(expr, ast.Name) and expr.id in names.names:
            return True
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in names.attr_sets
        ):
            return True
        if isinstance(expr, ast.Subscript):
            b = expr.value
            if (
                isinstance(b, ast.Attribute)
                and isinstance(b.value, ast.Name)
                and b.value.id == "self"
                and b.attr in names.attr_dict_of_set
            ):
                return True
        return False

    def _check_scope(self, ctx, scope, names, out) -> None:
        own_stmts = scope.body
        for node in [
            n for st in own_stmts for n in ast.walk(st)
        ]:
            if isinstance(node, (ast.For, ast.AsyncFor)):
                if self._is_set(node.iter, names):
                    out.append(self.finding(
                        ctx, node.iter, "for-loop " + self._FIX
                    ))
            elif isinstance(node, (
                ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp
            )):
                for gen in node.generators:
                    if self._is_set(gen.iter, names):
                        out.append(self.finding(
                            ctx, gen.iter, "comprehension " + self._FIX
                        ))
            elif isinstance(node, ast.Call):
                fn = node.func
                if (
                    isinstance(fn, ast.Name)
                    and fn.id in _ORDER_SENSITIVE
                    and node.args
                    and self._is_set(node.args[0], names)
                ):
                    out.append(self.finding(
                        ctx, node, f"{fn.id}(...) " + self._FIX
                    ))
                elif (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in ("extend", "join")
                    and node.args
                    and self._is_set(node.args[0], names)
                ):
                    out.append(self.finding(
                        ctx, node, f".{fn.attr}(...) " + self._FIX
                    ))
                elif (
                    isinstance(fn, ast.Attribute)
                    and fn.attr == "pop"
                    and not node.args
                    and self._is_set(fn.value, names)
                ):
                    out.append(self.finding(
                        ctx, node,
                        ".pop() takes a hash-order-arbitrary element "
                        "from a set; fix: pop from a sorted list or use "
                        "an explicit deterministic choice",
                    ))


# ---------------------------------------------------------------- SW003

_WALL_TIME_FNS = {"time", "sleep", "monotonic", "perf_counter",
                  "process_time", "time_ns", "monotonic_ns"}


class WallClockRule(Rule):
    id = "SW003"
    name = "wall-clock"
    describe = (
        "the transport/retry layer is logical-time (RetryPolicy ticks); "
        "wall-clock reads and sleeps diverge across nodes and replays"
    )
    # finality.py / flightrec.py take injected-clock callables and must
    # never read wall time themselves (byte-stable sim dumps depend on it)
    scope = (
        "transport.py", "oracle/node.py", "obs/finality.py",
        "obs/flightrec.py", "net/", "obs/cluster_trace.py",
        "obs/profile.py", "soak.py",
    )
    # net/ is the socket deployment edge: real deadlines, pacing, and tx
    # latency genuinely need wall time — but each read must say *why* at
    # the call site.  Only a justified line suppression
    # (``disable=SW003 -- <why>``) counts there; a bare disable or a
    # disable-file is still a finding, so the wall-clock surface of the
    # net layer stays enumerable and every entry self-documents.
    # obs/profile.py: the dispatch profiler's single timing callsite is
    # its one legitimate wall read — justified there, nowhere else.
    # soak.py drives real processes on a wall-clock schedule; same rule:
    # every wall read routes through frame.now()/frame.sleep() or a
    # justified line suppression.
    note_scope = ("net/", "obs/profile.py", "soak.py")

    _FIX = (
        "in the logical-time transport/retry layer; fix: advance the "
        "logical clock (RetryPolicy backoff ticks) or move timing to "
        "the obs layer outside these modules"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "time"
                and fn.attr in _WALL_TIME_FNS
            ):
                out.append(self.finding(
                    ctx, node, f"time.{fn.attr}() " + self._FIX
                ))
            elif (
                isinstance(fn, ast.Attribute)
                and fn.attr in ("now", "utcnow", "today")
                and isinstance(fn.value, (ast.Name, ast.Attribute))
                and (
                    getattr(fn.value, "id", None) == "datetime"
                    or getattr(fn.value, "attr", None) == "datetime"
                )
            ):
                out.append(self.finding(
                    ctx, node, f"datetime.{fn.attr}() " + self._FIX
                ))
        return out
