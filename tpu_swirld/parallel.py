"""SPMD sharding of the consensus pipeline over a ``jax.sharding.Mesh``.

Two shardings live here, matching the two drivers:

**Batch path (member axis).**  SURVEY.md §7 step 6 / BASELINE config 5:
the strongly-sees computation — the pipeline's FLOP bottleneck, Θ(N²·N)
boolean-matmul work — is sharded over the **member axis**: each device
owns M/D members, computes its members' ∃-z visibility hops as local
(N×K)@(K×N) matmuls, and the stake tallies are aggregated with
``lax.psum`` over the mesh.  Everything else (scans, fame, order) is
cheap and runs replicated.

**Streaming path (window axis).**  The batch sharding replicates the
visibility slabs on every device, which multiplies memory instead of
dividing it — exactly backwards for the streaming driver, whose whole
point is a bounded resident window.  :class:`MeshStreamingConsensus`
therefore **row-shards the window itself**: the ``anc``/``sees``/``ssm``
slabs live as ``P(axis, None)`` shards ((W/D, W) per device), every
from-scratch slab push goes through the driver's ``slab_put`` seam so
rebases and widenings scatter rows straight to their owners, and
:func:`make_row_sharded_block_fn` runs the extension block kernel with
one halo exchange — the gathered member rows each device owns, psum'd to
all — instead of an all-gather of the slab.  Per-device residency is
budgeted by :class:`~tpu_swirld.store.slab.SlabStore` (``n_shards`` /
``device_budget_tiles``).

Gossip stays a host-level concern exactly as in the reference's
in-process network dict; within the mesh, consensus-state reductions
ride ICI collectives inserted by XLA.

Multi-host note: the same ``shard_map`` code runs unchanged over a
multi-host mesh (``jax.distributed.initialize`` + a global device array);
the sharded axis then spans hosts and the psum rides DCN between ICI
domains.  The in-repo tests exercise an 8-device single-host mesh
(``xla_force_host_platform_device_count``), which the driver's
``dryrun_multichip`` hook replays.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_swirld import obs
from tpu_swirld.store.slab import SlabStore
from tpu_swirld.store.streaming import StreamingConsensus
from tpu_swirld.tpu.pipeline import _bmm, consensus_body

try:                                   # moved out of experimental in new JAX
    _shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map

MEMBER_AXIS = "members"

_STATIC = (
    "tot_stake",
    "coin_period",
    "block",
    "r_max",
    "s_max",
    "chain",
    "has_forks",
    "matmul_dtype_name",
)


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D member-axis mesh over the first ``n_devices`` devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    # (the mesh_devices gauge is recorded per run by run_consensus, the
    # point where an ambient Obs is reliably in scope)
    return Mesh(np.array(devs), (MEMBER_AXIS,))


def ssm_matrix_sharded(sees, member_table, stake, tot_stake, dtype, *, mesh):
    """Member-sharded strongly-sees: local matmul hops + psum stake tally.

    ``member_table`` rows and ``stake`` must be padded to a multiple of the
    mesh size (pad rows -1 / stake 0 — they contribute nothing).
    """

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(None, None), P(MEMBER_AXIS, None), P(MEMBER_AXIS)),
        out_specs=P(None, None),
    )
    def f(s, mt, stk):
        n = s.shape[0]

        def body(m, acc):
            idx = mt[m]
            valid = idx >= 0
            idxc = jnp.clip(idx, 0, n - 1)
            a = s[:, idxc] & valid[None, :]
            b = s[idxc, :] & valid[:, None]
            hit = _bmm(a, b, dtype)
            return acc + stk[m] * hit.astype(jnp.int32)

        # the per-device partial tally varies over the member axis; mark the
        # initial carry as varying so the fori_loop carry types line up
        # (pcast only exists once varying-type checking does — older
        # shard_map accepts the plain carry)
        acc0 = jnp.zeros((n, n), dtype=jnp.int32)
        if hasattr(lax, "pcast"):
            acc0 = lax.pcast(acc0, (MEMBER_AXIS,), to="varying")
        acc = lax.fori_loop(0, mt.shape[0], body, acc0)
        acc = lax.psum(acc, MEMBER_AXIS)
        return 3 * acc > 2 * tot_stake

    return f(sees, member_table, stake)


# Module-level kernel caches.  Keyed on the mesh's PHYSICAL identity
# (device ids + shape + axis names), never on the live Mesh object: a
# Mesh-keyed dict pins every mesh a test or bench round ever built —
# along with its compiled executables and device buffers — for the
# process lifetime, and two identical meshes miss each other's entries.
# Bounded FIFO so even a pathological sweep over many layouts stays flat.
_MESH_CACHE_MAX = 8


def _mesh_key(mesh: Mesh):
    return (
        tuple(int(d.id) for d in mesh.devices.flat),
        tuple(mesh.devices.shape),
        tuple(mesh.axis_names),
    )


def _mesh_cache_get(cache: dict, mesh: Mesh, build):
    key = _mesh_key(mesh)
    fn = cache.get(key)
    if fn is None:
        fn = build()
        cache[key] = fn
        while len(cache) > _MESH_CACHE_MAX:
            cache.pop(next(iter(cache)))
    return fn


_mesh_block_fns = {}
_mesh_row_block_fns = {}


def make_ssm_block_fn_for_mesh(mesh: Mesh):
    """Member-sharded strongly-sees *block* — the windowed counterpart of
    :func:`ssm_matrix_sharded`, matching the ``ssm_block_fn`` seam of
    :func:`tpu_swirld.tpu.pipeline.ssm_block_stage` /
    :class:`~tpu_swirld.tpu.pipeline.IncrementalConsensus`.

    Each device owns M/D member-table rows, gathers its members' row/
    column tiles straight from the (replicated) sees slab, computes the
    (rows, K) @ (K, C) ∃-z hops locally, and the int32 stake tallies ride
    one ``lax.psum`` over the member axis.  The member axis is padded to
    a mesh multiple here (pad rows are all-invalid and pad stake is 0, so
    they contribute nothing).  The same kernel serves the row-extension
    pass and the witness-column adds — exactly like the single-device
    stage, so the mesh driver rides every suffix-cut the host applies.
    """
    d = int(mesh.devices.size)

    def build():

        @functools.partial(
            jax.jit,
            static_argnames=("rows", "tot_stake", "matmul_dtype_name"),
        )
        def kernel(sees, member_table, stake, cols, row0, *, rows,
                   tot_stake, matmul_dtype_name):
            dtype = (
                jnp.bfloat16 if matmul_dtype_name == "bfloat16"
                else jnp.float32
            )
            m = member_table.shape[0]
            m_pad = ((m + d - 1) // d) * d
            if m_pad != m:
                member_table = jnp.pad(
                    member_table, ((0, m_pad - m), (0, 0)),
                    constant_values=-1,
                )
                stake = jnp.pad(stake, ((0, m_pad - m),))

            @functools.partial(
                _shard_map,
                mesh=mesh,
                in_specs=(
                    P(None, None),
                    P(MEMBER_AXIS, None),
                    P(MEMBER_AXIS),
                    P(None),
                    P(),
                ),
                out_specs=P(None, None),
            )
            def f(s, mtl, stkl, colsl, row0l):
                n = s.shape[0]
                ml, k = mtl.shape
                idx = mtl.reshape(-1)
                valid = idx >= 0
                idxc = jnp.clip(idx, 0, n - 1)
                colsc = jnp.clip(colsl, 0, n - 1)
                cv = colsl >= 0
                s_rows = lax.dynamic_slice(s, (row0l, 0), (rows, n))
                a_r3 = (
                    (s_rows[:, idxc] & valid[None, :])
                    .reshape(rows, ml, k).transpose(1, 0, 2)
                )
                b_cols = (
                    s[idxc[:, None], colsc[None, :]]
                    & valid[:, None] & cv[None, :]
                ).reshape(ml, k, colsl.shape[0])

                def body(mm, acc):
                    hit = _bmm(a_r3[mm], b_cols[mm], dtype)
                    return acc + stkl[mm] * hit.astype(jnp.int32)

                acc0 = jnp.zeros((rows, colsl.shape[0]), dtype=jnp.int32)
                if hasattr(lax, "pcast"):
                    acc0 = lax.pcast(acc0, (MEMBER_AXIS,), to="varying")
                acc = lax.fori_loop(0, ml, body, acc0)
                acc = lax.psum(acc, MEMBER_AXIS)
                return (3 * acc > 2 * tot_stake) & cv[None, :]

            return f(
                sees, member_table, stake, cols,
                jnp.asarray(row0, dtype=jnp.int32),
            )

        return kernel

    return _mesh_cache_get(_mesh_block_fns, mesh, build)


def make_row_sharded_block_fn(mesh: Mesh, *, bmm=None):
    """Window-row-sharded strongly-sees block — the streaming mesh's
    extension kernel, matching the ``ssm_block_fn`` seam of
    :func:`tpu_swirld.tpu.pipeline.ssm_block_stage`.

    The sees slab arrives as a ``P(axis, None)`` row shard: each device
    holds ``W/D`` window rows over the full column width, so the resident
    window *divides* across the mesh instead of replicating (the whole
    point of the streaming driver's memory bound).  The block then runs
    with exactly one halo exchange:

    - **b-side (the halo)**: of the ``M*K`` gathered member rows, each is
      resident on exactly one device; every device gathers the rows it
      owns (others masked to zero) and one int8 ``psum`` assembles the
      full ``(M*K, C)`` b-operand everywhere — an all-gather of only the
      K member rows per member, never of the slab.
    - **a-side (local)**: the extension rows ``[row0, row0 + rows)`` are
      gathered by their owning devices only; unowned rows are zero and
      contribute nothing to the stake tally.
    - one int32 ``psum`` sums the per-device tallies (each output row is
      computed by exactly one device), and the strict-2/3 threshold runs
      replicated.

    Exact: masks reproduce the single-device gathers bit-for-bit, and the
    start-index clamp matches ``lax.dynamic_slice`` semantics.  ``bmm``
    swaps the shard-local matmul hop (e.g. the Pallas tile kernel via
    :func:`tpu_swirld.tpu.pallas_kernels.make_extension_kernels`);
    ``None`` = the XLA :func:`~tpu_swirld.tpu.pipeline._bmm`.  Built
    kernels are cached per physical mesh (default ``bmm`` only — a custom
    hop owns its own lifetime)."""
    axis = mesh.axis_names[0]
    local_bmm = bmm if bmm is not None else _bmm

    def build():

        @functools.partial(
            jax.jit,
            static_argnames=("rows", "tot_stake", "matmul_dtype_name"),
        )
        def kernel(sees, member_table, stake, cols, row0, *, rows,
                   tot_stake, matmul_dtype_name):
            dtype = (
                jnp.bfloat16 if matmul_dtype_name == "bfloat16"
                else jnp.float32
            )

            @functools.partial(
                _shard_map,
                mesh=mesh,
                in_specs=(
                    P(axis, None),
                    P(None, None),
                    P(None),
                    P(None),
                    P(),
                ),
                out_specs=P(None, None),
            )
            def f(s_loc, mtl, stkl, colsl, row0l):
                n_loc, n = s_loc.shape
                c = colsl.shape[0]
                ml, k = mtl.shape
                dev0 = lax.axis_index(axis) * n_loc
                idx = mtl.reshape(-1)
                valid = idx >= 0
                idxc = jnp.clip(idx, 0, n - 1)
                colsc = jnp.clip(colsl, 0, n - 1)
                cv = colsl >= 0
                # ---- b-side halo: each gathered member row lives on one
                # device; owned contributions psum to the full operand
                loc_b = idxc - dev0
                own_b = (loc_b >= 0) & (loc_b < n_loc) & valid
                b_loc = (
                    s_loc[jnp.clip(loc_b, 0, n_loc - 1)][:, colsc]
                    & own_b[:, None] & cv[None, :]
                )
                b = lax.psum(b_loc.astype(jnp.int8), axis) > 0
                # ---- a-side: local rows only (clamp matches the
                # single-device dynamic_slice start semantics)
                row0c = jnp.clip(row0l, 0, n - rows)
                ridx = row0c - dev0 + jnp.arange(rows)
                rown = (ridx >= 0) & (ridx < n_loc)
                a = (
                    s_loc[jnp.clip(ridx, 0, n_loc - 1)][:, idxc]
                    & valid[None, :] & rown[:, None]
                )
                a_r3 = a.reshape(rows, ml, k).transpose(1, 0, 2)
                b_r3 = b.reshape(ml, k, c)

                def body(mm, acc):
                    hit = local_bmm(a_r3[mm], b_r3[mm], dtype)
                    return acc + stkl[mm] * hit.astype(jnp.int32)

                acc0 = jnp.zeros((rows, c), dtype=jnp.int32)
                if hasattr(lax, "pcast"):
                    acc0 = lax.pcast(acc0, (axis,), to="varying")
                acc = lax.fori_loop(0, ml, body, acc0)
                acc = lax.psum(acc, axis)
                return (3 * acc > 2 * tot_stake) & cv[None, :]

            return f(
                sees, member_table, stake, cols,
                jnp.asarray(row0, dtype=jnp.int32),
            )

        return kernel

    if bmm is not None:
        return build()
    return _mesh_cache_get(_mesh_row_block_fns, mesh, build)


class MeshStreamingConsensus(StreamingConsensus):
    """Streaming consensus with the resident window **row-sharded** over
    a mesh.

    The ``anc``/``sees``/``ssm`` slabs live as ``P(axis, None)`` shards —
    (W/D, ·) rows per device — so device memory is bounded by the
    undecided window *divided by the mesh*, not replicated across it:

    - every from-scratch slab push (cold-start rebase, widening rebase)
      rides the parent's ``slab_put`` seam and scatters rows straight to
      their owning devices;
    - the extension block kernel is :func:`make_row_sharded_block_fn`
      (one b-side halo psum + one stake-tally psum per block);
    - in-place jitted stages (extension writes, donated prune rolls)
      keep the placement via GSPMD propagation; growth paths that drift
      back to replicated are re-pinned after each ingest (counted in
      ``repins`` / the ``mesh_repins`` gauge — steady state is zero);
    - the :class:`~tpu_swirld.store.slab.SlabStore` accounts per-device
      residency (``n_shards=D``) and ``device_tile_budget`` bounds the
      widest shard exactly like the global budget.

    ``window_bucket`` is rounded up to a mesh multiple so every row
    capacity the driver ever allocates splits evenly across devices.
    The archive stays host-global: spills pull decided rows to the host
    exactly as on one device, and widening fetches scatter re-admitted
    rows back through ``slab_put``.
    """

    def __init__(
        self,
        mesh: Mesh,
        members,
        stake=None,
        config=None,
        *,
        tile_budget: Optional[int] = None,
        tile: int = 256,
        device_tile_budget: Optional[int] = None,
        strict_budget: bool = False,
        store: Optional[SlabStore] = None,
        bmm=None,
        pallas: bool = False,
        **kw,
    ):
        self.mesh = mesh
        d = int(mesh.devices.size)
        axis = mesh.axis_names[0]
        self._n_devices = d
        self._nsh = NamedSharding(mesh, P(axis, None))
        self.repins = 0
        wb = max(256, int(kw.pop("window_bucket", 1024)))
        wb = -(-wb // d) * d
        kw["window_bucket"] = wb
        kw.setdefault(
            "slab_put",
            lambda x: jax.device_put(np.asarray(x), self._nsh),
        )
        if pallas and bmm is None:
            # the Pallas MXU hop inside the same halo/psum pairing;
            # interpret-vs-compiled resolves via the capability probe
            # (compiled on TPU/GPU, interpret elsewhere — bit-identical)
            from tpu_swirld.tpu.pallas_kernels import make_mesh_row_block_fn

            kernel = make_mesh_row_block_fn(mesh)
        else:
            kernel = make_row_sharded_block_fn(mesh, bmm=bmm)
        kw.setdefault(
            "ssm_block_fn",
            functools.partial(
                obs.stage_call, "pipeline.ssm_block_mesh", kernel
            ),
        )
        if store is None:
            store = SlabStore(
                tile_budget, tile=tile, strict=strict_budget,
                config=config, n_shards=d,
                device_budget_tiles=device_tile_budget,
            )
        super().__init__(members, stake, config, store=store, **kw)
        self.flightrec_label = "streaming-mesh"

    # ----------------------------------------------------------- placement

    def _pinned(self, arr):
        try:
            ok = arr.sharding.is_equivalent_to(self._nsh, arr.ndim)
        except (AttributeError, TypeError):
            ok = False
        return arr if ok else None

    def _repin(self) -> int:
        """Re-scatter any slab whose placement drifted off the row shard
        (pad growth re-materializes; steady-state extension keeps it)."""
        if not self._initialized:
            return 0
        n = 0
        aliased = self._sees_d is self._anc_d
        if self._pinned(self._anc_d) is None:
            self._anc_d = jax.device_put(self._anc_d, self._nsh)
            n += 1
        if aliased:
            self._sees_d = self._anc_d
        elif self._pinned(self._sees_d) is None:
            self._sees_d = jax.device_put(self._sees_d, self._nsh)
            n += 1
        if self._pinned(self._ssm_d) is None:
            self._ssm_d = jax.device_put(self._ssm_d, self._nsh)
            n += 1
        if n:
            self._ars_cache = self._ars_key = None
            self.repins += n
            o = obs.current()
            if o is not None:
                o.registry.gauge("mesh_repins").set(self.repins)
        return n

    # ------------------------------------------------------------- ingest

    def ingest(self, events=()) -> dict:
        st = super().ingest(events)
        self._repin()
        st["mesh_devices"] = self._n_devices
        st["mesh_repins"] = self.repins
        return st


def streaming_consensus_for_mesh(
    mesh: Mesh, members, stake=None, config=None, **kw
):
    """A :class:`MeshStreamingConsensus` over ``mesh`` — the resident
    window row-sharded across devices, extension blocks running on
    row-local data with one halo exchange and one ``psum`` stake tally
    (and the same suffix cuts / slab donation as the single-device
    driver)."""
    return MeshStreamingConsensus(mesh, members, stake, config, **kw)


_mesh_fns = {}


def consensus_fn_for_mesh(mesh: Mesh):
    """Jitted end-to-end consensus with the SSM phase sharded over ``mesh``."""

    def build():
        def ssm_fn(sees, member_table, stake, tot_stake, dtype):
            return ssm_matrix_sharded(
                sees, member_table, stake, tot_stake, dtype, mesh=mesh
            )

        return functools.partial(jax.jit, static_argnames=_STATIC)(
            functools.partial(consensus_body, ssm_fn=ssm_fn)
        )

    return _mesh_cache_get(_mesh_fns, mesh, build)


def pad_members(member_table: np.ndarray, stake: np.ndarray, n_devices: int):
    """Pad the member axis to a multiple of the mesh size (-1 rows, 0 stake)."""
    m = member_table.shape[0]
    m_pad = ((m + n_devices - 1) // n_devices) * n_devices
    o = obs.current()
    if o is not None:
        o.registry.gauge("mesh_member_pad").set(m_pad - m)
    if m_pad == m:
        return member_table, stake
    extra = m_pad - m
    member_table = np.concatenate(
        [member_table, np.full((extra, member_table.shape[1]), -1, np.int32)]
    )
    stake = np.concatenate([stake, np.zeros((extra,), stake.dtype)])
    return member_table, stake
