"""SPMD sharding of the consensus pipeline over a ``jax.sharding.Mesh``.

SURVEY.md §7 step 6 / BASELINE config 5: the strongly-sees computation — the
pipeline's FLOP bottleneck, Θ(N²·N) boolean-matmul work — is sharded over
the **member axis**: each device owns M/D members, computes its members'
∃-z visibility hops as local (N×K)@(K×N) matmuls, and the stake tallies are
aggregated with ``lax.psum`` over the mesh (the "psum vote aggregation over
the member axis" the survey pins).  Everything else (scans, fame, order)
is cheap and runs replicated.

Gossip stays a host-level concern exactly as in the reference's in-process
network dict; within the mesh, consensus-state reductions ride ICI
collectives inserted by XLA.

Multi-host note: the same ``shard_map`` code runs unchanged over a
multi-host mesh (``jax.distributed.initialize`` + a global device array);
the member axis then spans hosts and the psum rides DCN between ICI
domains.  The in-repo tests exercise an 8-device single-host mesh
(``xla_force_host_platform_device_count``), which the driver's
``dryrun_multichip`` hook replays.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpu_swirld import obs
from tpu_swirld.tpu.pipeline import _bmm, consensus_body

try:                                   # moved out of experimental in new JAX
    _shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map

MEMBER_AXIS = "members"

_STATIC = (
    "tot_stake",
    "coin_period",
    "block",
    "r_max",
    "s_max",
    "chain",
    "has_forks",
    "matmul_dtype_name",
)


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D member-axis mesh over the first ``n_devices`` devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    # (the mesh_devices gauge is recorded per run by run_consensus, the
    # point where an ambient Obs is reliably in scope)
    return Mesh(np.array(devs), (MEMBER_AXIS,))


def ssm_matrix_sharded(sees, member_table, stake, tot_stake, dtype, *, mesh):
    """Member-sharded strongly-sees: local matmul hops + psum stake tally.

    ``member_table`` rows and ``stake`` must be padded to a multiple of the
    mesh size (pad rows -1 / stake 0 — they contribute nothing).
    """

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(None, None), P(MEMBER_AXIS, None), P(MEMBER_AXIS)),
        out_specs=P(None, None),
    )
    def f(s, mt, stk):
        n = s.shape[0]

        def body(m, acc):
            idx = mt[m]
            valid = idx >= 0
            idxc = jnp.clip(idx, 0, n - 1)
            a = s[:, idxc] & valid[None, :]
            b = s[idxc, :] & valid[:, None]
            hit = _bmm(a, b, dtype)
            return acc + stk[m] * hit.astype(jnp.int32)

        # the per-device partial tally varies over the member axis; mark the
        # initial carry as varying so the fori_loop carry types line up
        # (pcast only exists once varying-type checking does — older
        # shard_map accepts the plain carry)
        acc0 = jnp.zeros((n, n), dtype=jnp.int32)
        if hasattr(lax, "pcast"):
            acc0 = lax.pcast(acc0, (MEMBER_AXIS,), to="varying")
        acc = lax.fori_loop(0, mt.shape[0], body, acc0)
        acc = lax.psum(acc, MEMBER_AXIS)
        return 3 * acc > 2 * tot_stake

    return f(sees, member_table, stake)


_mesh_block_fns = {}


def make_ssm_block_fn_for_mesh(mesh: Mesh):
    """Member-sharded strongly-sees *block* — the windowed counterpart of
    :func:`ssm_matrix_sharded`, matching the ``ssm_block_fn`` seam of
    :func:`tpu_swirld.tpu.pipeline.ssm_block_stage` /
    :class:`~tpu_swirld.tpu.pipeline.IncrementalConsensus`.

    Each device owns M/D member-table rows, gathers its members' row/
    column tiles straight from the (replicated) sees slab, computes the
    (rows, K) @ (K, C) ∃-z hops locally, and the int32 stake tallies ride
    one ``lax.psum`` over the member axis.  The member axis is padded to
    a mesh multiple here (pad rows are all-invalid and pad stake is 0, so
    they contribute nothing).  The same kernel serves the row-extension
    pass and the witness-column adds — exactly like the single-device
    stage, so the mesh driver rides every suffix-cut the host applies.
    """
    d = int(mesh.devices.size)
    fn = _mesh_block_fns.get(mesh)
    if fn is None:

        @functools.partial(
            jax.jit,
            static_argnames=("rows", "tot_stake", "matmul_dtype_name"),
        )
        def kernel(sees, member_table, stake, cols, row0, *, rows,
                   tot_stake, matmul_dtype_name):
            dtype = (
                jnp.bfloat16 if matmul_dtype_name == "bfloat16"
                else jnp.float32
            )
            m = member_table.shape[0]
            m_pad = ((m + d - 1) // d) * d
            if m_pad != m:
                member_table = jnp.pad(
                    member_table, ((0, m_pad - m), (0, 0)),
                    constant_values=-1,
                )
                stake = jnp.pad(stake, ((0, m_pad - m),))

            @functools.partial(
                _shard_map,
                mesh=mesh,
                in_specs=(
                    P(None, None),
                    P(MEMBER_AXIS, None),
                    P(MEMBER_AXIS),
                    P(None),
                    P(),
                ),
                out_specs=P(None, None),
            )
            def f(s, mtl, stkl, colsl, row0l):
                n = s.shape[0]
                ml, k = mtl.shape
                idx = mtl.reshape(-1)
                valid = idx >= 0
                idxc = jnp.clip(idx, 0, n - 1)
                colsc = jnp.clip(colsl, 0, n - 1)
                cv = colsl >= 0
                s_rows = lax.dynamic_slice(s, (row0l, 0), (rows, n))
                a_r3 = (
                    (s_rows[:, idxc] & valid[None, :])
                    .reshape(rows, ml, k).transpose(1, 0, 2)
                )
                b_cols = (
                    s[idxc[:, None], colsc[None, :]]
                    & valid[:, None] & cv[None, :]
                ).reshape(ml, k, colsl.shape[0])

                def body(mm, acc):
                    hit = _bmm(a_r3[mm], b_cols[mm], dtype)
                    return acc + stkl[mm] * hit.astype(jnp.int32)

                acc0 = jnp.zeros((rows, colsl.shape[0]), dtype=jnp.int32)
                if hasattr(lax, "pcast"):
                    acc0 = lax.pcast(acc0, (MEMBER_AXIS,), to="varying")
                acc = lax.fori_loop(0, ml, body, acc0)
                acc = lax.psum(acc, MEMBER_AXIS)
                return (3 * acc > 2 * tot_stake) & cv[None, :]

            return f(
                sees, member_table, stake, cols,
                jnp.asarray(row0, dtype=jnp.int32),
            )

        fn = kernel
        _mesh_block_fns[mesh] = fn
    return fn


def streaming_consensus_for_mesh(
    mesh: Mesh, members, stake=None, config=None, **kw
):
    """A :class:`~tpu_swirld.store.streaming.StreamingConsensus` whose
    strongly-sees block kernel is sharded over ``mesh`` — tile work
    (the ``(rows, K) @ (K, C)`` member hops over the resident window)
    runs member-parallel with one ``psum`` stake tally, so the streaming
    path composes with the mesh exactly like the incremental one (and
    keeps riding the same extension kernels / suffix cuts)."""
    from tpu_swirld.store.streaming import StreamingConsensus

    kernel = make_ssm_block_fn_for_mesh(mesh)
    kw.setdefault(
        "ssm_block_fn",
        functools.partial(obs.stage_call, "pipeline.ssm_block_mesh", kernel),
    )
    return StreamingConsensus(members, stake, config, **kw)


_mesh_fns = {}


def consensus_fn_for_mesh(mesh: Mesh):
    """Jitted end-to-end consensus with the SSM phase sharded over ``mesh``."""
    fn = _mesh_fns.get(mesh)
    if fn is None:
        def ssm_fn(sees, member_table, stake, tot_stake, dtype):
            return ssm_matrix_sharded(
                sees, member_table, stake, tot_stake, dtype, mesh=mesh
            )

        fn = functools.partial(jax.jit, static_argnames=_STATIC)(
            functools.partial(consensus_body, ssm_fn=ssm_fn)
        )
        _mesh_fns[mesh] = fn
    return fn


def pad_members(member_table: np.ndarray, stake: np.ndarray, n_devices: int):
    """Pad the member axis to a multiple of the mesh size (-1 rows, 0 stake)."""
    m = member_table.shape[0]
    m_pad = ((m + n_devices - 1) // n_devices) * n_devices
    o = obs.current()
    if o is not None:
        o.registry.gauge("mesh_member_pad").set(m_pad - m)
    if m_pad == m:
        return member_table, stake
    extra = m_pad - m
    member_table = np.concatenate(
        [member_table, np.full((extra, member_table.shape[1]), -1, np.int32)]
    )
    stake = np.concatenate([stake, np.zeros((extra,), stake.dtype)])
    return member_table, stake
