"""The immutable hashgraph event record.

Mirrors the reference's five-field event (upstream ``swirld.py`` top:
``Event = namedtuple('Event', 'd p t c s')`` — SURVEY.md §2 component 1):
``d`` payload, ``p`` parent-hash pair (self-parent, other-parent; ``()``
for genesis), ``t`` creation timestamp, ``c`` creator public key, ``s``
detached signature over the serialized body.  The BLAKE2b hash of the
serialized body is the event's identity.

Serialization is a fixed, explicit byte layout (not pickle) so that event
IDs are stable across Python versions and host/device boundaries.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Optional, Tuple

from tpu_swirld import crypto


@dataclasses.dataclass(frozen=True)
class Event:
    d: bytes                       # payload (opaque transaction bytes)
    p: Tuple[bytes, ...]           # () for genesis, else (self_parent, other_parent)
    t: int                         # creation timestamp (integer; never float)
    c: bytes                       # creator public key
    s: bytes = b""                 # detached signature over body()

    def body(self) -> bytes:
        """Deterministic serialization of everything except the signature."""
        parts = [struct.pack("<B", len(self.p))]
        for ph in self.p:
            parts.append(ph)
        parts.append(struct.pack("<q", self.t))
        parts.append(struct.pack("<I", len(self.c)))
        parts.append(self.c)
        parts.append(struct.pack("<I", len(self.d)))
        parts.append(self.d)
        return b"".join(parts)

    @property
    def id(self) -> bytes:
        return crypto.hash_bytes(self.body())

    @property
    def self_parent(self) -> Optional[bytes]:
        return self.p[0] if self.p else None

    @property
    def other_parent(self) -> Optional[bytes]:
        return self.p[1] if self.p else None

    def signed(self, sk: bytes) -> "Event":
        return dataclasses.replace(self, s=crypto.sign(self.body(), sk))

    def verify(self) -> bool:
        return crypto.verify(self.body(), self.s, self.c)

    def coin_bit(self) -> int:
        return crypto.coin_bit(self.s)


def encode_event(ev: Event) -> bytes:
    """Wire encoding: body || sig (lengths are implicit in the body layout)."""
    body = ev.body()
    return struct.pack("<I", len(body)) + body + struct.pack("<I", len(ev.s)) + ev.s


def decode_event(data: bytes, offset: int = 0) -> Tuple[Event, int]:
    """Inverse of :func:`encode_event`; returns (event, next_offset)."""
    (blen,) = struct.unpack_from("<I", data, offset)
    offset += 4
    body = data[offset : offset + blen]
    offset += blen
    (slen,) = struct.unpack_from("<I", data, offset)
    offset += 4
    sig = data[offset : offset + slen]
    offset += slen

    # Parse the body layout written by Event.body().
    pos = 0
    (np_,) = struct.unpack_from("<B", body, pos)
    pos += 1
    parents = []
    for _ in range(np_):
        parents.append(body[pos : pos + crypto.HASH_BYTES])
        pos += crypto.HASH_BYTES
    (t,) = struct.unpack_from("<q", body, pos)
    pos += 8
    (clen,) = struct.unpack_from("<I", body, pos)
    pos += 4
    c = body[pos : pos + clen]
    pos += clen
    (dlen,) = struct.unpack_from("<I", body, pos)
    pos += 4
    d = body[pos : pos + dlen]
    pos += dlen
    return Event(d=d, p=tuple(parents), t=t, c=c, s=sig), offset
