"""The immutable hashgraph event record.

Mirrors the reference's five-field event (upstream ``swirld.py`` top:
``Event = namedtuple('Event', 'd p t c s')`` — SURVEY.md §2 component 1):
``d`` payload, ``p`` parent-hash pair (self-parent, other-parent; ``()``
for genesis), ``t`` creation timestamp, ``c`` creator public key, ``s``
detached signature over the serialized body.  The BLAKE2b hash of the
serialized body is the event's identity.

Serialization is a fixed, explicit byte layout (not pickle) so that event
IDs are stable across Python versions and host/device boundaries.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Optional, Tuple

from tpu_swirld import crypto


@dataclasses.dataclass(frozen=True)
class Event:
    d: bytes                       # payload (opaque transaction bytes)
    p: Tuple[bytes, ...]           # () for genesis, else (self_parent, other_parent)
    t: int                         # creation timestamp (integer; never float)
    c: bytes                       # creator public key
    s: bytes = b""                 # detached signature over body()

    def body(self) -> bytes:
        """Deterministic serialization of everything except the signature."""
        parts = [struct.pack("<B", len(self.p))]
        for ph in self.p:
            parts.append(ph)
        parts.append(struct.pack("<q", self.t))
        parts.append(struct.pack("<I", len(self.c)))
        parts.append(self.c)
        parts.append(struct.pack("<I", len(self.d)))
        parts.append(self.d)
        return b"".join(parts)

    @property
    def id(self) -> bytes:
        return crypto.hash_bytes(self.body())

    @property
    def self_parent(self) -> Optional[bytes]:
        return self.p[0] if self.p else None

    @property
    def other_parent(self) -> Optional[bytes]:
        return self.p[1] if self.p else None

    def signed(self, sk: bytes) -> "Event":
        return dataclasses.replace(
            self, s=crypto.sign(self.body(), sk, crypto.DOMAIN_EVENT)
        )

    def verify(self) -> bool:
        return crypto.verify(self.body(), self.s, self.c, crypto.DOMAIN_EVENT)

    def coin_bit(self) -> int:
        return crypto.coin_bit(self.s)


def encode_event(ev: Event) -> bytes:
    """Wire encoding: body || sig (lengths are implicit in the body layout)."""
    body = ev.body()
    return struct.pack("<I", len(body)) + body + struct.pack("<I", len(ev.s)) + ev.s


class MalformedEvent(ValueError):
    """Raised when a wire blob cannot be decoded as an event."""


MAX_PAYLOAD = 1 << 20          # 1 MiB payload cap on the wire
MAX_KEY = 1 << 10


def _take(data: bytes, pos: int, n: int, what: str) -> Tuple[bytes, int]:
    if n < 0 or pos + n > len(data):
        raise MalformedEvent(f"truncated {what} (need {n} bytes at {pos})")
    return data[pos : pos + n], pos + n


def decode_event(data: bytes, offset: int = 0) -> Tuple[Event, int]:
    """Inverse of :func:`encode_event`; returns (event, next_offset).

    Bounds-checked: malformed or truncated attacker-supplied bytes raise
    :class:`MalformedEvent` (a ``ValueError``) instead of crashing with
    ``struct.error`` or silently producing garbage slices.
    """
    raw, offset = _take(data, offset, 4, "body length")
    (blen,) = struct.unpack("<I", raw)
    if blen > 8 + MAX_PAYLOAD + MAX_KEY + 2 * crypto.HASH_BYTES + 16:
        raise MalformedEvent(f"oversized body ({blen} bytes)")
    body, offset = _take(data, offset, blen, "body")
    raw, offset = _take(data, offset, 4, "signature length")
    (slen,) = struct.unpack("<I", raw)
    if slen > 4 * crypto.SIG_BYTES:
        raise MalformedEvent(f"oversized signature ({slen} bytes)")
    sig, offset = _take(data, offset, slen, "signature")

    # Parse the body layout written by Event.body().
    raw, pos = _take(body, 0, 1, "parent count")
    np_ = raw[0]
    if np_ not in (0, 2):
        raise MalformedEvent(f"bad parent count {np_}")
    parents = []
    for _ in range(np_):
        ph, pos = _take(body, pos, crypto.HASH_BYTES, "parent hash")
        parents.append(ph)
    raw, pos = _take(body, pos, 8, "timestamp")
    (t,) = struct.unpack("<q", raw)
    raw, pos = _take(body, pos, 4, "creator length")
    (clen,) = struct.unpack("<I", raw)
    if clen > MAX_KEY:
        raise MalformedEvent(f"oversized creator key ({clen} bytes)")
    c, pos = _take(body, pos, clen, "creator")
    raw, pos = _take(body, pos, 4, "payload length")
    (dlen,) = struct.unpack("<I", raw)
    if dlen > MAX_PAYLOAD:
        raise MalformedEvent(f"oversized payload ({dlen} bytes)")
    d, pos = _take(body, pos, dlen, "payload")
    if pos != len(body):
        raise MalformedEvent(f"{len(body) - pos} trailing bytes in body")
    return Event(d=d, p=tuple(parents), t=t, c=c, s=sig), offset
