"""Graph utilities over the hash-linked event DAG.

The reference keeps these in ``utils.py`` (generator ``bfs`` / ``dfs`` and a
DFS-based ``toposort`` — SURVEY.md §2 component 5).  Same roles here, written
iteratively (no recursion limits) and deterministic: neighbors are visited in
the order the successor function yields them.
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, List, TypeVar

T = TypeVar("T")


def bfs(starts: Iterable[T], succ: Callable[[T], Iterable[T]]) -> Iterator[T]:
    """Breadth-first traversal from ``starts``; yields each node once."""
    seen = set()
    queue: List[T] = []
    for s in starts:
        if s not in seen:
            seen.add(s)
            queue.append(s)
    i = 0
    while i < len(queue):
        node = queue[i]
        i += 1
        yield node
        for nxt in succ(node):
            if nxt not in seen:
                seen.add(nxt)
                queue.append(nxt)


def dfs(starts: Iterable[T], succ: Callable[[T], Iterable[T]]) -> Iterator[T]:
    """Iterative depth-first traversal; yields each node once (preorder)."""
    seen = set()
    stack = list(starts)[::-1]
    while stack:
        node = stack.pop()
        if node in seen:
            continue
        seen.add(node)
        yield node
        children = list(succ(node))
        for nxt in reversed(children):
            if nxt not in seen:
                stack.append(nxt)


def toposort(nodes: Iterable[T], parents: Callable[[T], Iterable[T]]) -> List[T]:
    """Topological order (parents before children) of ``nodes``.

    Only nodes in ``nodes`` are ordered; parents outside the set are assumed
    already present downstream and are skipped.  Deterministic for a fixed
    input order.  Iterative post-order DFS.
    """
    node_set = set(nodes)
    out: List[T] = []
    done = set()
    in_progress = set()
    for root in nodes:
        if root in done:
            continue
        stack = [(root, False)]
        while stack:
            node, expanded = stack.pop()
            if node in done:
                continue
            if expanded:
                in_progress.discard(node)
                done.add(node)
                out.append(node)
                continue
            if node in in_progress:
                raise ValueError("cycle detected in event graph")
            in_progress.add(node)
            stack.append((node, True))
            for par in parents(node):
                if par in node_set and par not in done:
                    stack.append((par, False))
    return out
