"""Pure-Python reference oracle (the ground-truth consensus backend)."""

from tpu_swirld.oracle.event import Event
from tpu_swirld.oracle.node import Node

__all__ = ["Event", "Node"]
