"""The pure-Python reference ``Node`` — the consensus oracle.

This is a from-scratch implementation of Swirlds hashgraph consensus
(Baird, SWIRLDS-TR-2016-01) with the same public surface as the reference
prototype (upstream ``swirld.py``: ``Node.sync`` / ``ask_sync`` /
``divide_rounds`` / ``decide_fame`` / ``find_order`` — SURVEY.md §2,
BASELINE.json API pin).  It serves two roles:

1. The ``backend='python'`` consensus engine.
2. The bit-exactness oracle the TPU pipeline is property-tested against
   (`round` / `witness` / `famous` / consensus order must match exactly).

Precise rule choices (shared with :mod:`tpu_swirld.tpu.pipeline`; where the
unreadable reference left details ambiguous, these are OUR spec, documented
here so both backends agree):

- *ancestor*: reflexive-transitive parent closure (an event is its own
  ancestor).
- *fork*: two events by the same creator, neither an ancestor of the other.
  Minimal fork pairs always share (creator, seq); detection keys on that.
- *see*: ``x sees y`` iff ``y`` is an ancestor of ``x`` and ``x`` does NOT
  have a fork pair by ``y``'s creator among its ancestors.
- *strongly see*: ``x`` strongly sees ``y`` iff members holding a strict
  2/3-supermajority of stake each have an event ``z`` (ANY event by that
  member, not just a maximal tip) with ``x sees z`` and ``z sees y``.
  This is the normative ∃-z rule, implemented exactly on both backends
  (see :meth:`Node.strongly_sees`; pinned by a hand-built fork DAG test in
  ``tests/test_fork.py``).  All supermajorities are exact integer tests
  ``3*amount > 2*total``.
- *round*: ``r = max(parent rounds)``; promoted to ``r+1`` iff the event
  strongly sees round-``r`` witnesses whose creators hold a supermajority
  of stake (distinct creators counted once).  Genesis events are round 0.
- *witness*: first event of a creator in its round (genesis, or
  ``round > round(self_parent)``).
- *fame votes*: a round-``ry`` witness ``y`` votes on a round-``rx``
  witness ``x`` (``d = ry - rx``): at ``d == 1`` the vote is ``y sees x``;
  at ``d > 1`` tally over distinct creators of the round-``(ry-1)``
  witnesses ``y`` strongly sees — a creator contributes its stake to "yes"
  if any of its strongly-seen witnesses voted yes, and to "no" likewise.
  Majority value is ``yes >= no``.  In a non-coin round (``d % C != 0``) a
  supermajority tally decides fame; in a coin round a supermajority sets
  the vote, otherwise the vote is the middle bit of ``y``'s signature.
  Fame is the value of the chronologically first deciding round.
- *round received* of event ``x``: the first fame-complete round ``r``
  whose unique famous witnesses (famous witnesses whose creator has
  exactly one famous witness in ``r``) ALL have ``x`` as ancestor.  Rounds
  with zero unique famous witnesses receive nothing.
- *consensus timestamp*: lower-median (index ``(n-1)//2`` of the sorted
  list) of, per unique famous witness ``w``, the timestamp of the earliest
  self-ancestor of ``w`` that has ``x`` as an ancestor.
- *final order*: sort by (round received, consensus timestamp,
  ``BLAKE2b(whiten || id)``) where ``whiten`` is the XOR of the unique
  famous witnesses' signatures.
- *expiry horizon* (the deterministic ancient-event rule): an event is
  expired iff its round is at or below the fame-complete frontier of **its
  own ancestry** — a pure function of the DAG, so every node and every
  engine (oracle, batch pipeline, incremental driver) applies the identical
  cut.  Because ``round`` is monotone along ancestry (``r = max(parent
  rounds)`` plus promotion), the frontier of ``ancestry(x)`` is at most
  ``round(x) - 2`` (fame of round ``r`` needs a round-``r+2`` witness), so
  the deterministic cut **provably never fires** on a valid event.  The
  operational consequence: a witness is ALWAYS registered, no matter how
  late it arrives relative to this node's local commit progress.  Late
  arrivals into already-ordered rounds are tracked in
  :attr:`Node.late_witnesses` (observability only — they are full DAG
  citizens, ride sync replies like any event, and are decided not-famous
  by the existing vote structure whenever fewer than 1/3 of stake is
  equivocating; a late witness decided *famous* is flagged in
  :attr:`Node.horizon_violations` as an outside-BFT-model event).  This
  replaces the old node-local quarantine, whose cut depended on arrival
  timing and could make honest nodes permanently disagree on a round's
  unique-famous-witness set.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from tpu_swirld import crypto
from tpu_swirld.config import SwirldConfig
from tpu_swirld.obs import phase_scope
from tpu_swirld.oracle.event import Event, decode_event, encode_event
from tpu_swirld.oracle.graph import toposort
from tpu_swirld.transport import (
    CHANNEL_SYNC,
    CHANNEL_WANT,
    CircuitBreaker,
    RetryPolicy,
    Transport,
    TransportError,
)


def _bit_count(x: int) -> int:
    return x.bit_count()


def xor_bytes(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


class Node:
    """One hashgraph member: event store, gossip endpoint, consensus state."""

    def __init__(
        self,
        sk: bytes,
        pk: bytes,
        network: Dict[bytes, Callable],
        members: Sequence[bytes],
        config: Optional[SwirldConfig] = None,
        clock: Optional[Callable[[], int]] = None,
        create_genesis: bool = True,
        network_want: Optional[Dict[bytes, Callable]] = None,
        transport: Optional[Transport] = None,
    ):
        self.config = config or SwirldConfig(n_members=len(members))
        if len(members) != self.config.n_members:
            raise ValueError("members length != config.n_members")
        self.sk = sk
        self.pk = pk
        self.network = network
        self.network_want = network_want if network_want is not None else {}
        self._orphans: Dict[bytes, Event] = {}
        self._orphan_bytes = 0   # tracked against config.max_orphan_bytes
        self.bad_replies = 0   # malformed/mis-signed replies tolerated so far
        self.bad_requests = 0  # malformed requests served an empty reply
        self.retries = 0       # transport retry attempts issued
        self.backoff_total = 0.0  # cumulative backoff (logical ticks)
        # --- adversary detection counters (wired into node_gauges and the
        # report CLI's resilience section as adversary_*) ---
        self.equivocations_detected = 0   # fork groups seen (one per
                                          # detected (creator, seq) pair)
        self.withholding_suspected = 0    # pulls where a peer provably
                                          # held a parent it refused to
                                          # serve (see pull's want loop)
        self.budget_exhausted = 0         # forked creators beyond the
                                          # f = (n-1)//3 admission budget
        self.sync_branches_capped = 0     # ask_sync replies whose branch
                                          # walk hit max_fork_branches
        self.metrics = None   # set to metrics.Metrics() to enable counters
        self.tracer = None    # set to obs.Tracer() to record phase spans
        self.finality = None  # set to obs.FinalityTracker for per-event
                              # lifecycle tracking (rounds-to-decision,
                              # time-to-finality, gossip propagation)
        self.flightrec = None       # set via obs.flightrec.wire_node
        self.flightrec_label = None  # ring key for this node's entries
        self._tpu_engine = None   # lazily built when config.backend == "tpu"
        self.members: List[bytes] = list(members)
        self.member_index: Dict[bytes, int] = {m: i for i, m in enumerate(members)}
        stakes = self.config.stakes()
        self.stake: Dict[bytes, int] = {m: stakes[i] for i, m in enumerate(members)}
        self.tot_stake = sum(stakes)
        self._clock = clock or self._lamport_clock

        # --- gossip resilience: transport seam, retry policy, breaker ---
        # The default Transport routes over the same network dicts as the
        # pre-transport code (reliable, in-process); pass a FaultyTransport
        # to exercise the failure surface.
        self.transport = (
            transport
            if transport is not None
            else Transport(self.network, self.network_want)
        )
        cfg = self.config
        self.retry_policy = RetryPolicy(
            attempts=cfg.retry_attempts,
            backoff_base=cfg.retry_backoff,
            backoff_cap=cfg.retry_backoff_cap,
            jitter=cfg.retry_jitter,
            deadline=cfg.retry_deadline,
        )
        self.breaker = CircuitBreaker(
            clock=self._clock,
            failure_threshold=cfg.breaker_failures,
            misbehavior_threshold=cfg.breaker_misbehavior,
            cooldown=cfg.breaker_cooldown,
        )
        # deterministic per-node jitter stream (reproducible chaos runs)
        self._retry_rng = random.Random(
            int.from_bytes(crypto.hash_bytes(b"retry" + pk)[:8], "little")
            ^ cfg.seed
        )
        self._sleep: Optional[Callable[[float], None]] = None  # real
        # deployments may install time.sleep; sims keep time logical

        # --- event store / DAG ---
        self.hg: Dict[bytes, Event] = {}          # id -> Event
        self.idx: Dict[bytes, int] = {}           # id -> topo insertion index
        self.order_added: List[bytes] = []        # insertion (topo) order
        self.anc: Dict[bytes, int] = {}           # id -> ancestor bitmask (incl. self)
        self.seq: Dict[bytes, int] = {}           # id -> self-chain height
        self.member_mask: Dict[bytes, int] = {m: 0 for m in members}
        self.member_events: Dict[bytes, List[bytes]] = {m: [] for m in members}
        self.member_chain: Dict[bytes, List[bytes]] = {m: [] for m in members}
        self.by_seq: Dict[bytes, Dict[int, List[bytes]]] = {m: {} for m in members}
        self.branch_tips: Dict[bytes, set] = {m: set() for m in members}
        self.fork_groups: Dict[bytes, Dict[int, List[bytes]]] = {m: {} for m in members}
        self.has_fork: Dict[bytes, bool] = {m: False for m in members}
        self._forkseen_memo: Dict[Tuple[bytes, bytes], bool] = {}
        self.head: Optional[bytes] = None

        # --- consensus state ---
        self.round: Dict[bytes, int] = {}
        self.is_witness: Dict[bytes, bool] = {}
        self.witnesses: Dict[int, Dict[bytes, List[bytes]]] = {}  # r -> creator -> ids
        self.wit_list: Dict[int, List[bytes]] = {}                # r -> slot-ordered ids
        self.wit_slot: Dict[bytes, int] = {}                      # witness id -> slot
        self._ss_memo: Dict[Tuple[bytes, bytes], bool] = {}
        self.late_witnesses: List[bytes] = []  # witnesses that landed below
        #   the committed frontier (registered anyway — see the module
        #   docstring's expiry-horizon rule; metadata only)
        self.horizon_violations = 0  # late witnesses later decided FAMOUS
        #   (impossible under the n > 3f model; counted, never hidden)
        self.max_round = 0
        self.famous: Dict[bytes, Optional[bool]] = {}
        self.votes: Dict[Tuple[bytes, bytes], bool] = {}
        self._next_vote_round: Dict[bytes, int] = {}   # witness id -> next ry to process
        self._frozen_round = -1                        # rounds <= this are fame-complete

        # --- ordering state ---
        self.tbd: List[bytes] = []                 # insertion-ordered, not yet received
        self.round_received: Dict[bytes, int] = {}
        self.consensus_ts: Dict[bytes, int] = {}
        self.consensus: List[bytes] = []           # final total order (event ids)
        self.transactions: List[bytes] = []        # payloads in consensus order
        self.consensus_round = 0                   # next round to try ordering with

        # genesis event for self (skipped for pure observers replaying a
        # pre-built DAG that already contains this member's genesis)
        if create_genesis:
            genesis = Event(d=b"", p=(), t=self._now(), c=pk).signed(sk)
            self.add_event(genesis)
            self.divide_rounds([genesis.id])

    # ------------------------------------------------------------------ utils

    def _lamport_clock(self) -> int:
        return len(self.order_added)

    @property
    def orphans_parked(self) -> int:
        """Events parked awaiting missing parents (public gauge surface)."""
        return len(self._orphans)

    @property
    def forks_detected(self) -> int:
        """Members this node has seen fork (public gauge surface)."""
        return sum(1 for v in self.has_fork.values() if v)

    @property
    def quarantined_peers(self) -> int:
        """Peers with an open circuit breaker (public gauge surface)."""
        return len(self.breaker.quarantined()) if self.breaker else 0

    @property
    def circuit_opens(self) -> int:
        """Lifetime circuit-breaker open transitions (gauge surface)."""
        return self.breaker.opens if self.breaker else 0

    @property
    def undecided_window(self) -> int:
        """Events in store not yet in the decided order — how far
        consensus trails ingest.  The admission-control gauge: the tx
        ingestion layer (:mod:`tpu_swirld.net.ingest`) sheds client
        submissions while this exceeds its threshold, so an overloaded
        node backpressures instead of growing an unbounded queue."""
        return len(self.hg) - len(self.consensus)

    def _now(self) -> int:
        t = int(self._clock())
        if self.head is not None:
            t = max(t, self.hg[self.head].t + 1)
        return t

    # ------------------------------------------------- event creation / store

    def new_event(self, payload: bytes, other_parent: Optional[bytes]) -> Event:
        """Create and sign a new head event (genesis if no head yet)."""
        if self.head is None:
            parents: Tuple[bytes, ...] = ()
        else:
            if other_parent is None:
                raise ValueError("non-genesis event needs an other-parent")
            parents = (self.head, other_parent)
        return Event(d=payload, p=parents, t=self._now(), c=self.pk).signed(self.sk)

    def is_valid_event(self, ev: Event) -> bool:
        """Structural + cryptographic validation (reference: hash/signature/
        parent checks incl. fork-relevant creator constraints).

        Enforces the same size caps as the wire decoder — an event a peer
        could never decode must not enter the store (it would poison every
        future sync reply containing it).
        """
        from tpu_swirld.oracle.event import MAX_KEY, MAX_PAYLOAD

        if len(ev.d) > MAX_PAYLOAD or len(ev.c) > MAX_KEY:
            return False
        if ev.c not in self.member_index:
            return False
        if not ev.verify():
            return False
        if len(ev.p) not in (0, 2):
            return False
        if ev.p:
            sp, op = ev.p
            if sp not in self.hg or op not in self.hg:
                return False
            if self.hg[sp].c != ev.c:          # self-parent must share creator
                return False
            if self.hg[op].c == ev.c:          # other-parent must not
                return False
        return True

    def add_event(self, ev: Event) -> bool:
        """Insert a validated event; idempotent.  Returns True if new."""
        eid = ev.id
        if eid in self.hg:
            return False
        if not self.is_valid_event(ev):
            raise ValueError("invalid event")
        i = len(self.order_added)
        self.hg[eid] = ev
        self.idx[eid] = i
        self.order_added.append(eid)
        bit = 1 << i
        if ev.p:
            sp, op = ev.p
            self.anc[eid] = bit | self.anc[sp] | self.anc[op]
            self.seq[eid] = self.seq[sp] + 1
        else:
            self.anc[eid] = bit
            self.seq[eid] = 0
        c = ev.c
        s = self.seq[eid]
        self.member_mask[c] |= bit
        self.member_events[c].append(eid)
        # branch tips: events by c that are not (yet) anyone's self-parent.
        # Honest members keep a singleton; forked creators keep one tip per
        # live branch (ask_sync ships them so peers can want-list gaps).
        if ev.p:
            self.branch_tips[c].discard(ev.p[0])
        self.branch_tips[c].add(eid)
        group = self.by_seq[c].setdefault(s, [])
        group.append(eid)
        if len(group) == 2:
            # first fork at this (creator, seq)
            self._on_fork_group(c, s, group)
        if not self.has_fork[c]:
            self.member_chain[c].append(eid)   # index == seq while honest
        if c == self.pk:
            self.head = eid
        self.tbd.append(eid)
        if c != self.pk and self.finality is not None:
            # first remote arrival: creation stamp -> local tick is the
            # gossip-propagation latency (deduped inside the tracker)
            self.finality.record_gossip_arrival(eid, ev.t, now=self._clock())
        if self.flightrec is not None:
            self.flightrec.record_ingest(self.flightrec_label, eid)
        return True

    def _on_fork_group(self, c: bytes, s: int, group: List[bytes]) -> None:
        """Fork bookkeeping for the first pair at ``(creator, seq)``:
        ledger entry, detection counters, breaker strikes, and the n > 3f
        admission budget.  A dedicated seam so the model checker's
        mutation mode (``analysis.mc.mutations``) can seed a fork-blind
        bug here and prove the invariant catalog catches it."""
        newly_forked = not self.has_fork[c]
        self.fork_groups[c][s] = group
        self.has_fork[c] = True
        self.equivocations_detected += 1
        if self.metrics is not None:
            self.metrics.count("gossip_fork_pairs_detected")
            self.metrics.count("adversary_equivocations_detected")
        if (
            self.config.quarantine_forkers
            and self.breaker is not None
            and c != self.pk
        ):
            # fork detection feeds the breaker: a proven equivocator
            # is quarantined outright (its events still arrive via
            # honest relays; we just stop gossiping with it directly)
            self.breaker.record_misbehavior(
                c, weight=self.breaker.misbehavior_threshold
            )
        if newly_forked:
            self._check_fork_budget(c)

    def _check_fork_budget(self, c: bytes) -> None:
        """Explicit n > 3f admission check: the vote structure only
        tolerates f = (n-1)//3 equivocating creators.  Events beyond the
        budget are still admitted (fork PROOFS must keep flowing so every
        engine's fork ledger agrees), but the violation is surfaced —
        never silently absorbed — and the over-budget creator is cut off
        at the breaker even when quarantine_forkers is off."""
        f_budget = (len(self.members) - 1) // 3
        if self.forks_detected > f_budget:
            self.budget_exhausted += 1
            if self.metrics is not None:
                self.metrics.count("adversary_budget_exhausted")
            if self.breaker is not None and c != self.pk:
                self.breaker.record_misbehavior(
                    c, weight=self.breaker.misbehavior_threshold
                )

    def state_digest(self) -> bytes:
        """Canonical BLAKE2b digest of the consensus-relevant node state.

        Covers the store (event ids), per-event round / witness / fame /
        ordering assignments, the decided order, and the adversary
        counters — everything the invariant catalog reasons about.  The
        model checker (``analysis.mc``) uses it for counterexample
        replay bit-determinism: a schedule replayed twice must land on
        byte-identical digests at every step."""
        parts: List[bytes] = [len(self.hg).to_bytes(4, "little")]
        for eid in sorted(self.hg):
            parts.append(eid)
            parts.append(
                self.round.get(eid, -1).to_bytes(4, "little", signed=True)
            )
            parts.append(b"\x01" if self.is_witness.get(eid) else b"\x00")
            fam = self.famous.get(eid)
            parts.append(
                b"\x02" if fam is None else (b"\x01" if fam else b"\x00")
            )
            parts.append(
                self.round_received.get(eid, -1).to_bytes(
                    4, "little", signed=True
                )
            )
            parts.append(
                self.consensus_ts.get(eid, -1).to_bytes(8, "little", signed=True)
            )
        parts.append(len(self.consensus).to_bytes(4, "little"))
        parts.extend(self.consensus)
        for ctr in (
            self.forks_detected,
            self.equivocations_detected,
            self.budget_exhausted,
            len(self.late_witnesses),
            self.horizon_violations,
            self.bad_replies,
            self.bad_requests,
            self.withholding_suspected,
        ):
            parts.append(int(ctr).to_bytes(4, "little"))
        return crypto.hash_bytes(b"".join(parts))

    # ------------------------------------------------------------ visibility

    def in_anc(self, container: bytes, member_of: bytes) -> bool:
        """Is event ``member_of`` an ancestor of ``container``?"""
        return (self.anc[container] >> self.idx[member_of]) & 1 == 1

    def forkseen(self, eid: bytes, m: bytes) -> bool:
        """Does ``eid`` have a fork pair by member ``m`` among its ancestors?"""
        if not self.has_fork[m]:
            return False
        key = (eid, m)
        memo = self._forkseen_memo.get(key)
        if memo is not None:
            return memo
        a = self.anc[eid]
        result = False
        for _s, ids in self.fork_groups[m].items():
            hits = 0
            for fid in ids:
                if (a >> self.idx[fid]) & 1:
                    hits += 1
                    if hits >= 2:
                        result = True
                        break
            if result:
                break
        self._forkseen_memo[key] = result
        return result

    def sees(self, x: bytes, y: bytes) -> bool:
        """Fork-aware visibility: y ancestor of x, no fork by y's creator."""
        return self.in_anc(x, y) and not self.forkseen(x, self.hg[y].c)

    def _sees_through(self, x: bytes, w: bytes, m: bytes) -> bool:
        """∃ event z by member m with (x sees z) and (z sees w).

        For an honest (fork-free) m, z ranges over the prefix of m's
        self-chain that is in x's ancestry; ``anc(z, w)`` and
        ``forkseen(z, c(w))`` are both monotone along that chain, so the
        earliest chain event with ``anc(z, w)`` is the least likely to be
        fork-poisoned — a binary search decides ∃-z exactly.  For forked
        m, the few events are enumerated directly.
        """
        if self.forkseen(x, m):
            return False  # x sees no event by m at all
        cw = self.hg[w].c
        a = self.anc[x]
        if not self.has_fork[m]:
            cnt = _bit_count(a & self.member_mask[m])
            if not cnt:
                return False
            chain = self.member_chain[m]
            if not self.in_anc(chain[cnt - 1], w):
                return False
            lo, hi = 0, cnt - 1
            while lo < hi:
                mid = (lo + hi) // 2
                if self.in_anc(chain[mid], w):
                    hi = mid
                else:
                    lo = mid + 1
            return not self.forkseen(chain[lo], cw)
        for z in self.member_events[m]:
            if (
                (a >> self.idx[z]) & 1
                and self.in_anc(z, w)
                and not self.forkseen(z, cw)
            ):
                return True
        return False

    def strongly_sees(self, x: bytes, w: bytes) -> bool:
        """x strongly sees w: members holding a stake supermajority each
        have an event z with (x sees z) and (z sees w) — the ∃-z rule,
        exactly as documented in the module spec.  The batched device
        pipeline computes the same relation as a per-member visibility
        matmul (``tpu_swirld.tpu.pipeline``); parity tests pin the two."""
        if not self.in_anc(x, w):
            return False  # any valid z implies w is an ancestor of x
        key = (x, w)
        memo = self._ss_memo.get(key)
        if memo is not None:
            return memo
        amount = 0
        for m in self.members:
            if self._sees_through(x, w, m):
                amount += self.stake[m]
        result = 3 * amount > 2 * self.tot_stake
        self._ss_memo[key] = result
        return result

    # ---------------------------------------------------------------- gossip

    def heights(self) -> Dict[bytes, int]:
        """Per-member count of known events (the sync height vector)."""
        return {m: len(self.member_events[m]) for m in self.members}

    def ask_sync(self, from_pk: bytes, signed_heights: bytes) -> bytes:
        """Serve a sync: reply with the topo-sorted events the asker lacks.

        The asker's height vector is signed; the reply (concatenated encoded
        events) is signed by us.  (Reference contract: SURVEY.md §2 #4.)

        The count vector is only a *hint*: per-creator counts identify a
        chain prefix only while that creator is honest.  For creators we
        know to have forked, we send the delta above the count hint plus a
        bounded fork digest — the earliest fork group's siblings (the
        minimal equivocation proof, so an asker pinned to one branch
        always learns the fork exists) and the current branch tips (so its
        want-list can walk any branch it is missing).  Remaining gaps
        surface on the asker's side as orphans, which it recovers via
        :meth:`ask_events`; reply bytes per sync stay O(delta) even under
        a persistent equivocator (the old rule re-sent a forker's entire
        history on every sync forever).
        """
        if from_pk not in self.member_index:
            raise ValueError("unknown sync peer")
        if (
            len(signed_heights) < crypto.SIG_BYTES
            or len(signed_heights) > self.config.max_reply_bytes
        ):
            self.bad_requests += 1
            raise ValueError("truncated or oversized sync request")
        payload = signed_heights[: -crypto.SIG_BYTES]
        sig = signed_heights[-crypto.SIG_BYTES:]
        if not crypto.verify(payload, sig, from_pk, crypto.DOMAIN_SYNC_REQ):
            self.bad_requests += 1
            raise ValueError("bad sync-request signature")
        if len(payload) != 4 * len(self.members):
            self.bad_requests += 1
            raise ValueError("malformed sync-request height vector")
        heights: Dict[bytes, int] = {}
        off = 0
        for m in self.members:
            heights[m] = int.from_bytes(payload[off : off + 4], "little")
            off += 4
        missing: List[bytes] = []
        for m in self.members:
            known = self.member_events[m]
            if not self.has_fork[m]:
                missing.extend(known[heights[m]:])
                continue
            # Forked creator: the count hint cannot identify WHICH events
            # the asker holds (branches interleave differently per node),
            # so ship the recent tail of EVERY branch, at least as deep as
            # the count difference, plus the earliest fork group (the
            # minimal equivocation proof: an asker pinned to one branch
            # always learns the fork exists) and the branch tips.  The
            # count difference UNDER-estimates the true gap when the asker
            # holds branch events we lack (its surplus cancels our delta);
            # the tips close that residue — they orphan on the asker and
            # its want-list round-trips recover whole chain segments via
            # ask_events' self-ancestor expansion.  O(branches * delta)
            # per reply instead of the old O(full history).
            miss = max(len(known) - heights[m], 0)
            extra: set = set()
            # amplification bound: an equivocation storm can mint one
            # live branch per fork pair, making the tail walk — and the
            # reply — O(branches * delta) with unbounded branches.  Cap
            # the branches walked per reply (deterministic sorted
            # selection so every peer sees the same digest); the earliest
            # fork-group proof below always ships, and events on skipped
            # branches surface as orphan want-lists over later syncs.
            tips = sorted(self.branch_tips[m])
            cap = max(1, self.config.max_fork_branches)
            if len(tips) > cap:
                self.sync_branches_capped += 1
                if self.metrics is not None:
                    self.metrics.count("gossip_sync_branches_capped")
                tips = tips[:cap]
            for tip in tips:
                cur: Optional[bytes] = tip
                for _ in range(miss + 1):
                    if cur is None or cur in extra:
                        break
                    extra.add(cur)
                    cur = self.hg[cur].self_parent
            first_seq = min(self.fork_groups[m])
            extra.update(self.fork_groups[m][first_seq])
            missing.extend(sorted(extra))
        return self._sign_event_blob(missing)

    def _sign_event_blob(self, ids: List[bytes]) -> bytes:
        ordered = toposort(
            sorted(ids, key=lambda e: self.idx[e]),
            lambda e: [p for p in self.hg[e].p],
        )
        # reply-size caps, by count AND bytes: a topo *prefix* stays valid
        # to ingest, and the asker recovers the remainder through later
        # syncs / want-lists.  The byte cap must mirror the asker's
        # _decode_signed_blob budget — an over-budget reply would read as
        # misbehavior there, livelocking two honest peers forever.
        cap = self.config.max_reply_events
        if len(ordered) > cap:
            ordered = ordered[:cap]
        budget = self.config.max_reply_bytes - crypto.SIG_BYTES
        parts: List[bytes] = []
        size = 0
        for e in ordered:
            enc = encode_event(self.hg[e])
            if size + len(enc) > budget:
                break
            parts.append(enc)
            size += len(enc)
        blob = b"".join(parts)
        return blob + crypto.sign(blob, self.sk, crypto.DOMAIN_SYNC_REPLY)

    def ask_events(self, from_pk: bytes, signed_want: bytes) -> bytes:
        """Serve a want-list: the asker requests specific event ids (orphan
        parents it is missing); reply with those we have — each expanded
        into its self-ancestor chain, up to ``config.want_ancestor_depth``
        events per want (the wanted event included), so a single
        successful round-trip closes a whole chain gap instead of one
        parent level (events the asker already holds are idempotently
        skipped on its side; the reply caps still bound the blob) —
        topo-sorted and signed.  Unknown ids are silently skipped.

        Truncated / garbage / oversized requests (an attacker, or a lossy
        transport mangling bytes in flight) are answered with a signed
        EMPTY reply and counted in ``bad_requests`` — a byzantine asker
        must not be able to crash the serving side.
        """
        if from_pk not in self.member_index:
            raise ValueError("unknown sync peer")
        if (
            len(signed_want) < crypto.SIG_BYTES
            or len(signed_want) > self.config.max_reply_bytes
        ):
            return self._reject_request()
        payload = signed_want[: -crypto.SIG_BYTES]
        sig = signed_want[-crypto.SIG_BYTES:]
        if not crypto.verify(payload, sig, from_pk, crypto.DOMAIN_WANT):
            return self._reject_request()
        if len(payload) % crypto.HASH_BYTES:
            return self._reject_request()
        want = [
            payload[i : i + crypto.HASH_BYTES]
            for i in range(0, len(payload), crypto.HASH_BYTES)
        ]
        del want[self.config.max_reply_events:]   # cap the work we do
        # Ancestor expansion is breadth-first — level 0 serves every
        # requested id before any chain is walked deeper, so one want's
        # deep ancestry cannot starve the others — and respects the reply
        # cap: the blob is truncated to max_reply_events anyway, so
        # walking further is pure attacker-amplifiable waste.
        have: List[bytes] = []
        seen: set = set()
        cap = self.config.max_reply_events
        frontier = [h for h in want if h in self.hg]
        for _level in range(max(1, self.config.want_ancestor_depth)):
            if not frontier or len(have) >= cap:
                break
            nxt: List[bytes] = []
            for h in frontier:
                if len(have) >= cap:
                    break
                if h in seen:
                    continue
                seen.add(h)
                have.append(h)
                sp = self.hg[h].self_parent
                if sp is not None:
                    nxt.append(sp)
            frontier = nxt
        return self._sign_event_blob(have)

    def _reject_request(self) -> bytes:
        """Counted rejection of a malformed inbound request: a signed
        empty reply (decodes cleanly on an honest asker's side)."""
        self.bad_requests += 1
        if self.metrics is not None:
            self.metrics.count("gossip_bad_requests")
        return self._sign_event_blob([])

    def _decode_signed_blob(
        self, reply: bytes, peer_pk: bytes
    ) -> Optional[List[Event]]:
        """Decode a signed event blob; ``None`` on any malformation.

        Truncated, garbage, mis-signed, or oversized replies degrade to a
        *counted rejection* (``bad_replies`` + a misbehavior strike on the
        peer's circuit breaker) — never an uncaught exception.  The size
        cap bounds decode work before the signature is even checked.
        """
        if (
            len(reply) < crypto.SIG_BYTES
            or len(reply) > self.config.max_reply_bytes
        ):
            return self._reject_reply(peer_pk)
        blob = reply[: -crypto.SIG_BYTES]
        sig = reply[-crypto.SIG_BYTES:]
        if not crypto.verify(blob, sig, peer_pk, crypto.DOMAIN_SYNC_REPLY):
            return self._reject_reply(peer_pk)
        events: List[Event] = []
        off = 0
        try:
            while off < len(blob):
                ev, off = decode_event(blob, off)   # raises MalformedEvent
                events.append(ev)
        except ValueError:
            return self._reject_reply(peer_pk)
        return events

    def _reject_reply(self, peer_pk: bytes) -> None:
        self.bad_replies += 1
        if self.metrics is not None:
            self.metrics.count("gossip_bad_replies")
        if self.breaker is not None:
            self.breaker.record_misbehavior(peer_pk)
        return None

    def _ingest(self, events: Iterable[Event], new_ids: List[bytes]) -> None:
        """Insert events whose parents are known; park the rest as orphans,
        then drain the orphan buffer to a fixpoint."""
        for ev in events:
            eid = ev.id
            if eid in self.hg:
                continue
            if ev.p and any(p not in self.hg for p in ev.p):
                # park only events that are at least self-consistent (known
                # creator, size caps, valid signature, parent arity) — junk
                # must not be able to occupy the buffer; and evict FIFO when
                # over the count OR byte budget so poisoning can neither
                # disable recovery nor balloon memory (one valid signer
                # could otherwise park max_orphans * MAX_PAYLOAD bytes)
                if (
                    eid not in self._orphans   # re-sent: already parked
                    and self.config.max_orphans > 0
                    and len(ev.p) == 2
                    and self._plausible(ev)
                ):
                    cost = self._orphan_cost(ev)
                    if cost <= self.config.max_orphan_bytes:
                        while self._orphans and (
                            len(self._orphans) >= self.config.max_orphans
                            or self._orphan_bytes + cost
                            > self.config.max_orphan_bytes
                        ):
                            self._evict_orphan(next(iter(self._orphans)))
                        self._orphans[eid] = ev
                        self._orphan_bytes += cost
                continue
            try:
                if self.add_event(ev):
                    new_ids.append(eid)
            except ValueError:
                pass   # invalid event in a signed reply: drop, don't crash
        # fixpoint drain: an inserted orphan may unblock other orphans
        progress = True
        while progress and self._orphans:
            progress = False
            for eid, ev in list(self._orphans.items()):
                if not ev.p or all(p in self.hg for p in ev.p):
                    self._evict_orphan(eid)
                    try:
                        if self.add_event(ev):
                            new_ids.append(eid)
                            progress = True
                    except ValueError:
                        pass   # invalid orphan: drop it

    @staticmethod
    def _orphan_cost(ev: Event) -> int:
        """Approximate resident bytes of a parked event (wire size)."""
        return len(ev.d) + len(ev.c) + len(ev.s) + 2 * crypto.HASH_BYTES + 24

    def _evict_orphan(self, eid: bytes) -> None:
        ev = self._orphans.pop(eid)
        self._orphan_bytes -= self._orphan_cost(ev)

    def _plausible(self, ev: Event) -> bool:
        """Parent-independent validity: creator, size caps, signature."""
        from tpu_swirld.oracle.event import MAX_KEY, MAX_PAYLOAD

        return (
            len(ev.d) <= MAX_PAYLOAD
            and len(ev.c) <= MAX_KEY
            and ev.c in self.member_index
            and ev.verify()
        )

    def _missing_parents(self) -> List[bytes]:
        return sorted(
            {
                p
                for ev in self._orphans.values()
                for p in ev.p
                if p not in self.hg and p not in self._orphans
            }
        )

    def _transport_call(
        self, peer_pk: bytes, channel: str, payload: bytes
    ) -> Optional[bytes]:
        """One logical request over the transport with bounded retry.

        Transport failures (drops, partitions, timeouts, crashed peers)
        are retried up to ``retry_policy.attempts`` times with exponential
        backoff + per-node deterministic jitter, stopping early when the
        per-peer deadline is exhausted or the circuit breaker opens.
        Backoff is *logical*: delays are recorded (``backoff_total``,
        ``gossip_backoff_time``) and handed to ``self._sleep`` if one is
        installed — simulations never block on wall-clock sleeps.

        Returns the raw reply, or ``None`` when the call ultimately
        failed (always counted, never raised).
        """
        met = self.metrics
        pol = self.retry_policy
        br = self.breaker
        attempts = max(1, pol.attempts)
        spent = 0.0
        result: Optional[bytes] = None
        for attempt in range(attempts):
            try:
                result = self.transport.call(
                    self.pk, peer_pk, channel, payload
                )
                if not isinstance(result, (bytes, bytearray)):
                    # a non-bytes reply is peer garbage, not a reply
                    raise ValueError("non-bytes reply")
                break
            except TransportError:
                if met is not None:
                    met.count("gossip_transport_errors")
                if br is not None:
                    before = br.opens
                    br.record_failure(peer_pk)
                    if br.opens > before:
                        if met is not None:
                            met.count("gossip_circuit_opens")
                        break   # breaker just opened: stop hammering
                if attempt + 1 >= attempts:
                    break
                delay = pol.backoff(attempt, self._retry_rng)
                if spent + delay > pol.deadline:
                    if met is not None:
                        met.count("gossip_deadline_exceeded")
                    break
                spent += delay
                self.retries += 1
                if met is not None:
                    met.count("gossip_retries")
                    met.count("gossip_backoff_time", delay)
                if self._sleep is not None:
                    self._sleep(delay)
            except ValueError:
                # legacy direct-dict path: the peer rejected our request —
                # attributable misbehavior (or our bug), not retryable
                self.bad_replies += 1
                if met is not None:
                    met.count("gossip_bad_replies")
                if br is not None:
                    br.record_misbehavior(peer_pk)
                self.backoff_total += spent
                return None
        self.backoff_total += spent
        return result

    def pull(self, peer_pk: bytes) -> List[bytes]:
        """Receive the peer's delta (no own-event creation).

        Resilient by construction: transport failures retry with backoff
        (:meth:`_transport_call`), malformed replies degrade to counted
        rejections, unknown-parent events park in the orphan buffer with
        want-list recovery, and peers that keep failing or misbehaving are
        quarantined by the circuit breaker (calls fail fast until a
        cooldown elapses).  ``pull`` never raises on peer behavior.
        """
        new_ids: List[bytes] = []
        met = self.metrics
        br = self.breaker
        if br is not None and not br.allow(peer_pk):
            if met is not None:
                met.count("gossip_circuit_fastfail")
            return new_ids
        hv = b"".join(
            len(self.member_events[m]).to_bytes(4, "little") for m in self.members
        )
        req = hv + crypto.sign(hv, self.sk, crypto.DOMAIN_SYNC_REQ)
        if met is not None:
            met.count("gossip_syncs")
            met.count("gossip_bytes_out", len(req))
        reply = self._transport_call(peer_pk, CHANNEL_SYNC, req)
        if reply is None:
            return new_ids
        events = self._decode_signed_blob(reply, peer_pk)
        if events is None:
            return new_ids
        if br is not None:
            br.record_success(peer_pk)
        if met is not None:
            met.count("gossip_bytes_in", len(reply))
        # parents referenced by events THIS peer served us this pull: the
        # peer's own store admitted those events, so it provably held the
        # parents too (add_event requires both parents present) — the
        # evidence base for the withholding heuristic below
        served_parents: set = set()
        for ev in events:
            served_parents.update(ev.p)
        self._ingest(events, new_ids)
        # want-list recovery: bounded by DAG depth, capped defensively
        has_want = self.transport.endpoint(peer_pk, CHANNEL_WANT) is not None
        for _ in range(self.config.max_want_rounds):
            want = self._missing_parents()
            if not want or not has_want:
                break
            wv = b"".join(want)
            wreq = wv + crypto.sign(wv, self.sk, crypto.DOMAIN_WANT)
            if met is not None:
                met.count("gossip_want_roundtrips")
                met.count("gossip_bytes_out", len(wreq))
            wreply = self._transport_call(peer_pk, CHANNEL_WANT, wreq)
            if wreply is None:
                break
            got = self._decode_signed_blob(wreply, peer_pk)
            if got is None:
                break
            if met is not None:
                met.count("gossip_bytes_in", len(wreply))
            # withholding detection: a validly-signed want reply that
            # omits a parent of an event the SAME peer served us this
            # pull is (near-)proof of selective censorship — the peer
            # demonstrably held that parent when it admitted the child.
            # "Suspected", not proven: an in-flight-corrupted want
            # request is answered with a signed empty reply, which looks
            # identical here — hence a mild breaker strike, not the full
            # equivocation escalation.
            got_ids = {ev.id for ev in got}
            withheld = [
                w for w in want
                if w not in got_ids and w in served_parents
            ]
            if withheld:
                self.withholding_suspected += 1
                if met is not None:
                    met.count("adversary_withholding_suspected")
                if br is not None:
                    br.record_misbehavior(peer_pk)
            if not got:
                break
            for ev in got:
                served_parents.update(ev.p)
            before = len(new_ids) + len(self._orphans)
            self._ingest(got, new_ids)
            if len(new_ids) + len(self._orphans) == before:
                break   # no progress: stop asking this peer
        if met is not None:
            met.count("gossip_events_received", len(new_ids))
        return new_ids

    def sync(self, peer_pk: bytes, payload: bytes) -> List[bytes]:
        """Gossip with ``peer_pk``; returns new event ids in topo order
        (received sub-DAG first, then our freshly created event)."""
        new_ids = self.pull(peer_pk)
        peer_events = self.member_events[peer_pk]
        if not peer_events:
            return new_ids
        peer_head = peer_events[-1]
        mine = self.new_event(payload, peer_head)
        self.add_event(mine)
        new_ids.append(mine.id)
        return new_ids

    # ------------------------------------------------------------- consensus

    def _register_witness(self, eid: bytes, r: int) -> None:
        # Deterministic expiry horizon (module docstring): the only sound
        # node-agreed cut — "expired iff below the fame-complete frontier
        # of the event's own ancestry" — provably never fires, so EVERY
        # witness registers, however late it lands relative to this node's
        # commit progress.  A late registration (round at or below the
        # already-ordered frontier) cannot change committed state: votes
        # are memoized pure functions of fixed ancestries, no existing
        # witness strongly sees the newcomer, and a committed round's UFW
        # set only changes if the newcomer is decided famous — which the
        # vote-unanimity lemma rules out below 1/3 equivocating stake
        # (tracked in horizon_violations otherwise).  This is what keeps
        # the live oracle, a batch replay, and every peer bit-identical
        # regardless of arrival order.
        if r <= self._frozen_round:
            self.late_witnesses.append(eid)
            if self.metrics is not None:
                self.metrics.count("consensus_late_witnesses")
        self.is_witness[eid] = True
        slots = self.wit_list.setdefault(r, [])
        # slot order (insertion order) is load-bearing: decide_fame scans
        # wit_list in slot order and the device pipeline mirrors it.
        self.wit_slot[eid] = len(slots)
        slots.append(eid)
        self.witnesses.setdefault(r, {}).setdefault(self.hg[eid].c, []).append(eid)
        self.famous[eid] = None
        self._next_vote_round[eid] = r + 1

    def _parent_round(self, sp: bytes, op: bytes) -> int:
        """Base round of a new event before witness promotion: the max of
        its parents' rounds.  A seam for the model checker's round-skew
        mutation (``analysis.mc.mutations``) — the round-monotonicity
        invariant must catch any regression here."""
        return max(self.round[sp], self.round[op])

    def divide_rounds(self, new_ids: Iterable[bytes]) -> None:
        """Assign round numbers and witness flags to ``new_ids`` (topo order).

        Reference: ``Node.divide_rounds`` (SURVEY.md §2 #6) — hot loop 1.
        """
        for eid in new_ids:
            ev = self.hg[eid]
            if not ev.p:
                self.round[eid] = 0
                self._register_witness(eid, 0)
                continue
            sp, op = ev.p
            r = self._parent_round(sp, op)
            # promotion: strongly-seen round-r witnesses, distinct creators
            amount = 0
            for c, wids in self.witnesses.get(r, {}).items():
                if any(self.strongly_sees(eid, w) for w in wids):
                    amount += self.stake[c]
            if 3 * amount > 2 * self.tot_stake:
                r += 1
            self.round[eid] = r
            self.max_round = max(self.max_round, r)
            if self.round[sp] < r:
                self._register_witness(eid, r)
            else:
                self.is_witness[eid] = False

    def _vote_tally(self, y: bytes, x: bytes, ry: int) -> Tuple[int, int]:
        """Stake tallies (yes, no) over distinct creators of the round-(ry-1)
        witnesses y strongly sees, using their (lazily computed) votes on x."""
        yes = no = 0
        for c, wids in self.witnesses.get(ry - 1, {}).items():
            c_yes = c_no = False
            for w in wids:
                if self.strongly_sees(y, w):
                    if self._vote(w, x):
                        c_yes = True
                    else:
                        c_no = True
            if c_yes:
                yes += self.stake[c]
            if c_no:
                no += self.stake[c]
        return yes, no

    def _vote(self, y: bytes, x: bytes) -> bool:
        """The vote of witness y on witness x — a memoized pure function of
        the DAG (strongly-seen witnesses are ancestors of y, so every vote a
        tally references exists whenever y exists; arrival order cannot
        change any value)."""
        key = (y, x)
        memo = self.votes.get(key)
        if memo is not None:
            return memo
        d = self.round[y] - self.round[x]
        if d <= 1:
            v = self.sees(y, x)
        else:
            yes, no = self._vote_tally(y, x, self.round[y])
            v = yes >= no
            if d % self.config.coin_period == 0 and not (
                3 * max(yes, no) > 2 * self.tot_stake
            ):
                v = bool(self.hg[y].coin_bit())  # coin flip from signature
        self.votes[key] = v
        return v

    def decide_fame(self) -> None:
        """Virtual fame voting (reference ``Node.decide_fame``, hot loop 2).

        Fame of x is the majority value at the chronologically first
        non-coin round where some witness's tally reaches a stake
        supermajority.  Vote values are pure functions of the DAG
        (see :meth:`_vote`), so incremental processing converges to the
        same fame assignment as a batch pass over the final DAG.
        """
        C = self.config.coin_period
        for rx in sorted(self.wit_list):
            for x in self.wit_list[rx]:
                if self.famous[x] is not None:
                    continue
                for ry in range(max(self._next_vote_round[x], rx + 2), self.max_round + 1):
                    d = ry - rx
                    decided = False
                    if d % C != 0:
                        for y in self.wit_list.get(ry, []):
                            yes, no = self._vote_tally(y, x, ry)
                            if 3 * max(yes, no) > 2 * self.tot_stake:
                                self.famous[x] = yes >= no
                                decided = True
                                if self.famous[x] and rx <= self._frozen_round:
                                    # a late witness decided FAMOUS would
                                    # retroactively change a committed
                                    # round's UFW set — impossible below
                                    # 1/3 equivocating stake; surfaced,
                                    # never silently absorbed
                                    self.horizon_violations += 1
                                    if self.metrics is not None:
                                        self.metrics.count(
                                            "consensus_horizon_violations"
                                        )
                                break
                    self._next_vote_round[x] = ry + 1
                    if decided:
                        break

    def _fame_complete(self, r: int) -> bool:
        if self.max_round < r + 2:
            return False
        return all(self.famous[w] is not None for w in self.wit_list.get(r, []))

    def _self_chain(self, w: bytes) -> List[bytes]:
        """w's self-ancestor chain, genesis first (explicit pointer walk so
        forked creators are handled)."""
        chain = []
        cur: Optional[bytes] = w
        while cur is not None:
            chain.append(cur)
            cur = self.hg[cur].self_parent
        chain.reverse()
        return chain

    def _earliest_seeing_ts(self, w: bytes, x: bytes) -> int:
        """Timestamp of the earliest self-ancestor of w that has x as an
        ancestor (binary search: ancestry is monotone along the self-chain)."""
        chain = self._self_chain(w)
        lo, hi = 0, len(chain) - 1
        while lo < hi:
            mid = (lo + hi) // 2
            if self.in_anc(chain[mid], x):
                hi = mid
            else:
                lo = mid + 1
        return self.hg[chain[lo]].t

    def find_order(self) -> None:
        """Extract the consensus order (reference ``Node.find_order``, hot
        loop 3).  Processes fame-complete rounds in ascending order."""
        while self._fame_complete(self.consensus_round):
            r = self.consensus_round
            # unique famous witnesses: creators with exactly one famous witness
            ufw: List[bytes] = []
            for c, wids in self.witnesses.get(r, {}).items():
                fam = [w for w in wids if self.famous[w]]
                if len(fam) == 1:
                    ufw.append(fam[0])
            ufw.sort(key=lambda w: self.idx[w])
            self._frozen_round = r
            self.consensus_round += 1
            if not ufw:
                continue
            whiten = bytes(crypto.SIG_BYTES)
            for w in ufw:
                whiten = xor_bytes(whiten, self.hg[w].s)
            received: List[Tuple[int, bytes, bytes]] = []
            remaining: List[bytes] = []
            for x in self.tbd:
                if all(self.in_anc(w, x) for w in ufw):
                    ts = sorted(self._earliest_seeing_ts(w, x) for w in ufw)
                    med = ts[(len(ts) - 1) // 2]
                    self.round_received[x] = r
                    self.consensus_ts[x] = med
                    tie = crypto.hash_bytes(whiten + x)
                    received.append((med, tie, x))
                else:
                    remaining.append(x)
            self.tbd = remaining
            received.sort(key=lambda item: (item[0], item[1]))
            fin = self.finality
            now = self._clock() if fin is not None else None
            for med, _tie, x in received:
                self.consensus.append(x)
                self.transactions.append(self.hg[x].d)
                if fin is not None:
                    # rounds_to_decision = round_received - round is a pure
                    # DAG function; birth is the event's creation stamp, so
                    # time_to_finality is logical ticks under a sim clock
                    fin.record_decided(
                        x, self.round[x], r, birth=self.hg[x].t, now=now,
                    )
            if fin is not None and received:
                fin.set_watermark(
                    self.flightrec_label
                    if self.flightrec_label is not None
                    else self.pk[:4].hex(),
                    len(self.consensus), r,
                )

    # ------------------------------------------------------------- main loop

    def consensus_pass(self, new_ids: List[bytes]) -> None:
        """The three consensus calls in reference order — the pluggable
        seam.  ``config.backend == "tpu"`` routes the pass through the
        batched device pipeline (:mod:`tpu_swirld.backend`), producing
        bit-identical state."""
        if self.config.backend == "tpu":
            if self._tpu_engine is None:
                from tpu_swirld.backend import TpuEngine

                self._tpu_engine = TpuEngine(self)
            if self.metrics is None and self.tracer is None:
                self._tpu_engine.consensus_pass(new_ids)
            else:
                before = len(self.consensus) if self.metrics is not None else 0
                with phase_scope(self.metrics, self.tracer, "tpu_pipeline"):
                    self._tpu_engine.consensus_pass(new_ids)
                if self.metrics is not None:
                    self.metrics.count("events_processed", len(new_ids))
                    self.metrics.count(
                        "events_ordered", len(self.consensus) - before
                    )
            return
        if self.metrics is None and self.tracer is None:
            self.divide_rounds(new_ids)
            self.decide_fame()
            self.find_order()
            return
        before = len(self.consensus) if self.metrics is not None else 0
        with phase_scope(self.metrics, self.tracer, "divide_rounds"):
            self.divide_rounds(new_ids)
        with phase_scope(self.metrics, self.tracer, "decide_fame"):
            self.decide_fame()
        with phase_scope(self.metrics, self.tracer, "find_order"):
            self.find_order()
        if self.metrics is not None:
            self.metrics.count("events_processed", len(new_ids))
            self.metrics.count("events_ordered", len(self.consensus) - before)

    def main(self, pick_peer: Callable[[], bytes], payload_fn=None):
        """Coroutine: each resume gossips with one random peer and runs a
        consensus pass (reference ``Node.main``)."""
        while True:
            payload = payload_fn() if payload_fn else b""
            peer = pick_peer()
            new_ids = self.sync(peer, payload)
            self.consensus_pass(new_ids)
            yield new_ids
