"""Configuration for the hashgraph framework.

The reference keeps its constants inline in source (coin period ``C = 6``,
stake passed to ``Node.__init__``, sim sizes as function args — SURVEY.md §5
"Config / flag system: none").  Here they live in one dataclass shared by the
oracle, the simulator, and the TPU pipeline so that both backends always agree
on the protocol parameters.

Archive knobs additionally honor ``SWIRLD_ARCHIVE_*`` environment
variables so a deployment can retune the background spill pipeline
without touching code: an explicit ``SwirldConfig`` field wins, then the
environment variable, then the built-in default (see
:func:`resolve_archive_settings`).  The flight-recorder knobs
(``SWIRLD_FLIGHTREC_*``, :func:`resolve_flightrec_settings`) and the
socket/cluster knobs (``SWIRLD_NET_*``, :func:`resolve_net_settings`)
follow the same precedence.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Tuple

#: built-in archive defaults (field -> (env var, default, parser))
_ARCHIVE_ENV = {
    "archive_compress_level": ("SWIRLD_ARCHIVE_COMPRESS_LEVEL", 1, int),
    "archive_queue_depth": ("SWIRLD_ARCHIVE_QUEUE_DEPTH", 8, int),
    "archive_async": (
        "SWIRLD_ARCHIVE_ASYNC", True,
        lambda v: v.strip().lower() not in ("0", "", "no", "false", "off"),
    ),
}


#: built-in flight-recorder defaults (field -> (env var, default, parser)).
#: Same precedence as the archive knobs: explicit SwirldConfig field >
#: SWIRLD_FLIGHTREC_* env var > built-in default.
_FLIGHTREC_ENV = {
    "flightrec_capacity": ("SWIRLD_FLIGHTREC_CAPACITY", 256, int),
    "flightrec_max_dumps": ("SWIRLD_FLIGHTREC_MAX_DUMPS", 16, int),
    "flightrec_dir": ("SWIRLD_FLIGHTREC_DIR", None, str),
}


def resolve_flightrec_settings(
    config: Optional["SwirldConfig"] = None,
) -> Dict:
    """Concrete flight-recorder settings: explicit config field >
    ``SWIRLD_FLIGHTREC_*`` env var > built-in default.  Returns
    ``{"capacity", "max_dumps", "dump_dir"}`` (``dump_dir`` may be
    ``None`` = record in memory, never auto-dump)."""
    out = {}
    names = {
        "flightrec_capacity": "capacity",
        "flightrec_max_dumps": "max_dumps",
        "flightrec_dir": "dump_dir",
    }
    for field, (env, default, parse) in _FLIGHTREC_ENV.items():
        v = getattr(config, field, None) if config is not None else None
        if v is None:
            raw = os.environ.get(env)
            v = parse(raw) if raw is not None else default
        out[names[field]] = v
    return out


#: built-in socket/cluster defaults (field -> (env var, default, parser)).
#: Same precedence as the archive/flightrec knobs: explicit SwirldConfig
#: field > SWIRLD_NET_* env var > built-in default.  Units are wall
#: seconds (the net layer is the deployment edge; consensus stays
#: logical-time) except the byte/count caps.
_NET_ENV = {
    "net_connect_timeout_s": ("SWIRLD_NET_CONNECT_TIMEOUT", 5.0, float),
    "net_call_timeout_s": ("SWIRLD_NET_CALL_TIMEOUT", 10.0, float),
    "net_max_frame_bytes": (
        "SWIRLD_NET_MAX_FRAME", (1 << 24) + (1 << 16), int,
    ),
    "net_tx_batch_bytes": ("SWIRLD_NET_TX_BATCH_BYTES", 64 << 10, int),
    "net_tx_max_bytes": ("SWIRLD_NET_TX_MAX_BYTES", 16 << 10, int),
    "net_tx_pool_txs": ("SWIRLD_NET_TX_POOL", 4096, int),
    "net_max_undecided": ("SWIRLD_NET_MAX_UNDECIDED", 2048, int),
    "net_gossip_interval_s": ("SWIRLD_NET_GOSSIP_INTERVAL", 0.01, float),
    "net_checkpoint_every_s": ("SWIRLD_NET_CHECKPOINT_EVERY", 1.0, float),
    "net_retry_tick_s": ("SWIRLD_NET_RETRY_TICK", 0.02, float),
    "net_redial_probe_s": ("SWIRLD_NET_REDIAL_PROBE", 0.05, float),
}


def resolve_net_settings(config: Optional["SwirldConfig"] = None) -> Dict:
    """Concrete socket/cluster settings: explicit config field >
    ``SWIRLD_NET_*`` env var > built-in default.  Returns
    ``{"connect_timeout_s", "call_timeout_s", "max_frame_bytes",
    "tx_batch_bytes", "tx_max_bytes", "tx_pool_txs", "max_undecided",
    "gossip_interval_s", "checkpoint_every_s", "retry_tick_s",
    "redial_probe_s"}`` (plain values, never ``None``).
    ``retry_tick_s`` converts the logical backoff ticks
    :class:`~tpu_swirld.transport.RetryPolicy` computes into real sleep
    seconds for socket deployments; ``redial_probe_s`` bounds the single
    re-probe wait after a failed transparent redial (a peer mid-restart
    whose new listener is not yet bound)."""
    out = {}
    for field, (env, default, parse) in _NET_ENV.items():
        v = getattr(config, field, None) if config is not None else None
        if v is None:
            raw = os.environ.get(env)
            v = parse(raw) if raw is not None else default
        out[field[len("net_"):]] = v
    return out


#: built-in production-day-soak defaults (field -> (env var, default,
#: parser)).  Same precedence as every other knob family: explicit
#: SwirldConfig field > SWIRLD_SOAK_* env var > built-in default.  The
#: soak orchestrator (:mod:`tpu_swirld.soak`) reads these for its spec
#: defaults; wall-second units, like the net knobs — the soak is a
#: deployment-edge harness, never part of the consensus core.
_SOAK_ENV = {
    "soak_horizon_s": ("SWIRLD_SOAK_HORIZON", 8.0, float),
    "soak_nodes": ("SWIRLD_SOAK_NODES", 4, int),
    "soak_tx_rate": ("SWIRLD_SOAK_TX_RATE", 150.0, float),
    "soak_clients": ("SWIRLD_SOAK_CLIENTS", 3, int),
    "soak_tx_bytes": ("SWIRLD_SOAK_TX_BYTES", 64, int),
    "soak_pareto_alpha": ("SWIRLD_SOAK_PARETO_ALPHA", 1.5, float),
    "soak_finality_budget_s": ("SWIRLD_SOAK_FINALITY_BUDGET", 6.0, float),
}


def resolve_soak_settings(config: Optional["SwirldConfig"] = None) -> Dict:
    """Concrete production-day-soak settings: explicit config field >
    ``SWIRLD_SOAK_*`` env var > built-in default.  Returns
    ``{"horizon_s", "nodes", "tx_rate", "clients", "tx_bytes",
    "pareto_alpha", "finality_budget_s"}`` (plain values, never
    ``None``).  ``finality_budget_s`` is the composite verdict's p99
    submission→decided latency ceiling; ``pareto_alpha`` shapes the
    traffic generator's heavy-tailed inter-arrival draw."""
    out = {}
    for field, (env, default, parse) in _SOAK_ENV.items():
        v = getattr(config, field, None) if config is not None else None
        if v is None:
            raw = os.environ.get(env)
            v = parse(raw) if raw is not None else default
        out[field[len("soak_"):]] = v
    return out


#: built-in streaming-dispatch defaults (field -> (env var, default,
#: parser)).  Same precedence as the archive knobs: explicit SwirldConfig
#: field > SWIRLD_* env var > built-in default.
_STREAM_ENV = {
    "fuse_chunks": ("SWIRLD_FUSE_CHUNKS", 8, int),
    "decode_overlap": (
        "SWIRLD_DECODE_OVERLAP", True,
        lambda v: v.strip().lower() not in ("0", "", "no", "false", "off"),
    ),
    "decode_queue_depth": ("SWIRLD_DECODE_QUEUE_DEPTH", 2, int),
}


def resolve_stream_settings(config: Optional["SwirldConfig"] = None) -> Dict:
    """Concrete streaming-dispatch settings: explicit config field >
    ``SWIRLD_FUSE_CHUNKS`` / ``SWIRLD_DECODE_*`` env var > built-in
    default.  Returns ``{"fuse_chunks", "decode_overlap",
    "decode_queue_depth"}`` (plain values, never ``None``).
    ``fuse_chunks <= 1`` disables dispatch fusion (the per-chunk loop);
    ``decode_overlap`` toggles the streaming driver's gossip-decode
    worker (results are identical either way — drain barriers serialize
    every packer handoff)."""
    out = {}
    for field, (env, default, parse) in _STREAM_ENV.items():
        v = getattr(config, field, None) if config is not None else None
        if v is None:
            raw = os.environ.get(env)
            v = parse(raw) if raw is not None else default
        out[field] = v
    return out


def resolve_archive_settings(config: Optional["SwirldConfig"] = None) -> Dict:
    """Concrete archive settings: explicit config field > ``SWIRLD_ARCHIVE_*``
    env var > built-in default.  Returns ``{"compress_level", "queue_depth",
    "async_spill"}`` (plain values, never ``None``)."""
    out = {}
    names = {
        "archive_compress_level": "compress_level",
        "archive_queue_depth": "queue_depth",
        "archive_async": "async_spill",
    }
    for field, (env, default, parse) in _ARCHIVE_ENV.items():
        v = getattr(config, field, None) if config is not None else None
        if v is None:
            raw = os.environ.get(env)
            v = parse(raw) if raw is not None else default
        out[names[field]] = v
    return out


@dataclasses.dataclass(frozen=True)
class SwirldConfig:
    """Protocol + engine parameters.

    Attributes:
      n_members: number of members (nodes) in the population.
      stake: per-member stake; ``None`` means one unit each.  Supermajority
        is *strictly more than* 2/3 of total stake, evaluated in exact
        integer arithmetic (``3 * x > 2 * tot``) on both backends.
      coin_period: every ``coin_period``-th fame-voting round is a coin
        round (the reference's ``C = 6``).
      backend: ``"python"`` (oracle) or ``"tpu"`` (batched JAX pipeline) —
        the pluggable seam named in BASELINE.json.
      seed: base RNG seed for simulations.
      mesh_shape: device mesh as ``{axis_name: size}`` for the sharded
        pipeline; ``None`` → single device.
      block_size: event-block tile for the blockwise ancestry kernel.
      max_rounds: static bound on the number of created rounds for device
        tables (checked against the actual data; raise if exceeded).
    """

    n_members: int = 4
    stake: Optional[Tuple[int, ...]] = None
    coin_period: int = 6
    backend: str = "python"
    seed: int = 0
    mesh_shape: Optional[Dict[str, int]] = None
    block_size: int = 256
    max_rounds: int = 256
    max_orphans: int = 4096      # unknown-parent events parked per node
    max_orphan_bytes: int = 8 << 20  # byte budget for the orphan buffer
                                 # (count cap alone admits ~4 GiB of
                                 # max-payload events from one signer)
    max_want_rounds: int = 32    # want-list round-trips per sync
    want_ancestor_depth: int = 64  # ask_events ships, per wanted event, a
                                 # self-ancestor chain of up to this many
                                 # events (the wanted event included), so
                                 # one successful want round-trip closes a
                                 # whole chain gap (not one parent level)
                                 # — deep-orphan recovery under loss
    tpu_min_batch: int = 1       # backend='tpu': min new events per device
                                 # pass (higher amortizes the batch replay;
                                 # consensus output is identical, delayed)

    # --- gossip resilience (transport retry / reply caps / quarantine) ---
    # Retry/backoff units are logical clock ticks (see transport.RetryPolicy);
    # nothing sleeps — the sim records delays, real deployments may sleep.
    retry_attempts: int = 3      # total transport attempts per call
    retry_backoff: float = 1.0   # first-retry backoff (doubles per retry)
    retry_backoff_cap: float = 8.0
    retry_jitter: float = 0.5    # extra uniform [0, jitter*delay] per retry
    retry_deadline: float = 16.0  # per-peer total backoff budget per pull
    breaker_failures: int = 4    # consecutive transport failures to open
    breaker_misbehavior: int = 12  # attributable-garbage strikes to open
    breaker_cooldown: float = 24.0  # ticks before a half-open probe
    max_reply_bytes: int = 1 << 24  # reject larger sync/want replies
    max_reply_events: int = 65536   # server-side cap on events per reply
    quarantine_forkers: bool = False  # detected equivocators trip the
                                      # circuit breaker immediately
    max_fork_branches: int = 8   # sync-reply amplification bound: branch
                                 # tails walked per forked creator per
                                 # reply (deterministic sorted selection;
                                 # the earliest fork-group proof always
                                 # ships, residue recovers via want-lists)

    # --- slab archive / background spill pipeline (store.archive) ---
    # None = fall back to SWIRLD_ARCHIVE_* env var, then built-in default
    # (resolve_archive_settings).
    archive_compress_level: Optional[int] = None  # zlib level for spilled
                                                  # rows (default 1)
    archive_queue_depth: Optional[int] = None     # bounded spill-queue depth;
                                                  # a full queue backpressures
                                                  # the spiller (default 8)
    archive_async: Optional[bool] = None          # background packing worker
                                                  # on/off (default on; results
                                                  # are identical either way —
                                                  # drain barriers serialize
                                                  # every read)

    # --- streaming dispatch fusion / ingest-decode overlap ---
    # None = fall back to SWIRLD_FUSE_CHUNKS / SWIRLD_DECODE_* env var,
    # then built-in default (resolve_stream_settings).
    fuse_chunks: Optional[int] = None   # scan chunks fused per rounds
                                        # dispatch (<=1 = per-chunk loop;
                                        # default 8).  Outputs are bit-
                                        # identical at every value.
    decode_overlap: Optional[bool] = None   # streaming gossip-decode
                                            # worker on/off (default on;
                                            # async == sync bit-identical
                                            # — drain barriers serialize
                                            # every packer handoff)
    decode_queue_depth: Optional[int] = None  # bounded decode-queue depth
                                              # (double-buffer default 2)

    # --- black-box flight recorder (obs.flightrec) ---
    # None = fall back to SWIRLD_FLIGHTREC_* env var, then built-in
    # default (resolve_flightrec_settings).
    flightrec_capacity: Optional[int] = None  # ring entries kept per node
                                              # (default 256)
    flightrec_max_dumps: Optional[int] = None  # post-mortem dump files per
                                               # recorder before triggers
                                               # stop writing (default 16)
    flightrec_dir: Optional[str] = None       # dump directory; None =
                                              # in-memory only, no files

    # --- socket transport / real-process cluster (net/) ---
    # None = fall back to SWIRLD_NET_* env var, then built-in default
    # (resolve_net_settings).  Wall-second knobs live HERE, at the
    # deployment edge; the consensus core stays logical-time.
    net_connect_timeout_s: Optional[float] = None  # TCP connect deadline
    net_call_timeout_s: Optional[float] = None     # per-RPC reply deadline
    net_max_frame_bytes: Optional[int] = None      # frame ceiling (must
                                                   # admit max_reply_bytes)
    net_tx_batch_bytes: Optional[int] = None       # tx batch payload cap
    net_tx_max_bytes: Optional[int] = None         # per-tx size cap
    net_tx_pool_txs: Optional[int] = None          # pending-pool cap
    net_max_undecided: Optional[int] = None        # undecided-window
                                                   # admission threshold
    net_gossip_interval_s: Optional[float] = None  # gossip loop pacing
    net_checkpoint_every_s: Optional[float] = None  # checkpoint cadence
    net_retry_tick_s: Optional[float] = None       # seconds per logical
                                                   # RetryPolicy backoff tick

    # --- dynamic membership (membership/) ---
    membership_delay: int = 4    # rounds between a membership tx's decision
                                 # (round_received of its carrier) and the
                                 # first round the new MemberEpoch governs

    def stakes(self) -> Tuple[int, ...]:
        if self.stake is not None:
            if len(self.stake) != self.n_members:
                raise ValueError(
                    f"stake has {len(self.stake)} entries for "
                    f"{self.n_members} members"
                )
            return tuple(int(s) for s in self.stake)
        return tuple(1 for _ in range(self.n_members))

    @property
    def total_stake(self) -> int:
        return sum(self.stakes())

    def supermajority(self, amount: int) -> bool:
        """True iff ``amount`` is strictly more than 2/3 of total stake."""
        return 3 * amount > 2 * self.total_stake
