"""Configuration for the hashgraph framework.

The reference keeps its constants inline in source (coin period ``C = 6``,
stake passed to ``Node.__init__``, sim sizes as function args — SURVEY.md §5
"Config / flag system: none").  Here they live in one dataclass shared by the
oracle, the simulator, and the TPU pipeline so that both backends always agree
on the protocol parameters.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class SwirldConfig:
    """Protocol + engine parameters.

    Attributes:
      n_members: number of members (nodes) in the population.
      stake: per-member stake; ``None`` means one unit each.  Supermajority
        is *strictly more than* 2/3 of total stake, evaluated in exact
        integer arithmetic (``3 * x > 2 * tot``) on both backends.
      coin_period: every ``coin_period``-th fame-voting round is a coin
        round (the reference's ``C = 6``).
      backend: ``"python"`` (oracle) or ``"tpu"`` (batched JAX pipeline) —
        the pluggable seam named in BASELINE.json.
      seed: base RNG seed for simulations.
      mesh_shape: device mesh as ``{axis_name: size}`` for the sharded
        pipeline; ``None`` → single device.
      block_size: event-block tile for the blockwise ancestry kernel.
      max_rounds: static bound on the number of created rounds for device
        tables (checked against the actual data; raise if exceeded).
    """

    n_members: int = 4
    stake: Optional[Tuple[int, ...]] = None
    coin_period: int = 6
    backend: str = "python"
    seed: int = 0
    mesh_shape: Optional[Dict[str, int]] = None
    block_size: int = 256
    max_rounds: int = 256
    max_orphans: int = 4096      # unknown-parent events parked per node
    max_want_rounds: int = 32    # want-list round-trips per sync
    tpu_min_batch: int = 1       # backend='tpu': min new events per device
                                 # pass (higher amortizes the batch replay;
                                 # consensus output is identical, delayed)

    def stakes(self) -> Tuple[int, ...]:
        if self.stake is not None:
            if len(self.stake) != self.n_members:
                raise ValueError(
                    f"stake has {len(self.stake)} entries for "
                    f"{self.n_members} members"
                )
            return tuple(int(s) for s in self.stake)
        return tuple(1 for _ in range(self.n_members))

    @property
    def total_stake(self) -> int:
        return sum(self.stakes())

    def supermajority(self, amount: int) -> bool:
        """True iff ``amount`` is strictly more than 2/3 of total stake."""
        return 3 * amount > 2 * self.total_stake
