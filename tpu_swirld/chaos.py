"""Chaos harness: end-to-end fault-injection scenarios with verdicts.

:class:`ChaosSimulation` composes every resilience mechanism in one run:

- gossip routed through a :class:`~tpu_swirld.transport.FaultyTransport`
  (seeded drops / corruption / duplication / reordering / delays,
  scheduled partitions);
- node-side retry/backoff, counted rejections, and circuit-breaker
  quarantine (``config.quarantine_forkers`` is ON: detected equivocators
  are cut off directly and only reach honest nodes via relays);
- peer **crashes**: a crashed member loses its in-memory state entirely
  (its endpoints are torn down), then restarts from its last
  :mod:`tpu_swirld.checkpoint` file plus its **own-event WAL** — the
  standard BFT requirement that a signer never lose its own signing
  history (cf. Tendermint's priv-validator state): without it a restart
  re-signs at an old sequence number and equivocates against its own
  lost tip, and every such *amnesia fork* burns one slot of the ``n >
  3f`` budget.  Restore then replays forward via gossip, with pull-only
  *recovery sweeps* (orphan/want-list recovery fetches the missing
  other-parents of WAL events) before the node creates new events;
- optional byzantine members (:class:`~tpu_swirld.sim.DivergentForker`)
  riding the same faulty transport, so network and byzantine faults
  compose.

The run produces a **verdict** dict asserting the two protocol claims:

- *safety*: every honest node's decided consensus order is bit-identical
  to a prefix of a fault-free **oracle replay** — a fresh observer node
  that ingests the union of all honest event stores over a reliable
  transport and recomputes consensus from scratch (consensus is a pure
  function of the DAG, so this is the ground truth the chaos run must
  agree with) — and all honest decided prefixes agree pairwise;
- *liveness*: decided rounds keep advancing after partitions heal and
  crashed nodes restart.

Scenarios are reproducible from ``(scenario.seed, plan.seed)``:
``scripts/chaos_run.py`` is the CLI front end and
``tests/test_chaos.py`` pins the acceptance scenario.  Two named storm
scenarios ride the same machinery: :func:`run_horizon_storm` (straggler
witnesses across a healing partition — the deterministic expiry horizon's
acceptance gate, with a cross-engine bit-parity verdict) and
:func:`run_overflow_storm` (witness-table self-healing: fork-storm slot
doubling and the unclamped round-window retry).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, List, Optional

from tpu_swirld.checkpoint import load_node, save_node
from tpu_swirld.config import SwirldConfig
from tpu_swirld.oracle.graph import toposort
from tpu_swirld.oracle.node import Node
from tpu_swirld.sim import DivergentForker, attach_obs, build_population
from tpu_swirld.transport import FaultPlan, FaultyTransport, Partition


def oracle_replay(
    union: Dict[bytes, "object"],
    members: List[bytes],
    config: SwirldConfig,
    observer_key,
    node_cls: type = None,
) -> List[bytes]:
    """Fault-free ground truth for a union event store: a fresh observer
    ingests ``union`` (id -> Event) in deterministic topo order and
    recomputes consensus from scratch.  Consensus is a pure function of
    the DAG, so this is the order every honest participant must have
    decided a prefix of — shared by the in-process chaos verdict and the
    real-process cluster verdict (:mod:`tpu_swirld.net.cluster`), which
    rebuilds ``union`` from the per-process event logs."""
    ordered = toposort(
        sorted(union, key=lambda e: (union[e].t, e)),
        lambda e: [p for p in union[e].p],
    )
    pk, sk = observer_key
    # node_cls: a dynamic-membership population must be replayed by a
    # DynamicNode observer — a static observer would keep genesis stake
    # past decided epoch boundaries and diverge from every honest node
    observer = (node_cls or Node)(
        sk=sk, pk=pk, network={}, members=members,
        config=config, create_genesis=False,
    )
    new_ids = []
    for eid in ordered:
        if observer.add_event(union[eid]):
            new_ids.append(eid)
    observer.consensus_pass(new_ids)
    return observer.consensus


def safety_section(
    orders: List[List[bytes]], oracle: List[bytes],
) -> Dict:
    """The verdict's safety block: all honest decided orders agree on
    their common prefix AND each is bit-identical to a prefix of the
    fault-free oracle replay."""
    m = min(len(o) for o in orders) if orders else 0
    return {
        "prefix_agree": all(o[:m] == orders[0][:m] for o in orders),
        "oracle_agree": all(o == oracle[:len(o)] for o in orders),
        "common_prefix_len": m,
        "oracle_len": len(oracle),
    }


def liveness_section(
    decided_final: int,
    decided_at_heal: Optional[int],
    heal_turn,
) -> Dict:
    """The verdict's liveness block: the decided frontier advanced past
    the last fault window (``heal_turn == 0`` means a fault-free run —
    any progress counts)."""
    heal_base = decided_at_heal if decided_at_heal is not None else 0
    return {
        "decided_at_heal": heal_base,
        "decided_final": decided_final,
        "advanced_after_heal": decided_final > heal_base or heal_turn == 0,
        "heal_turn": heal_turn,
    }


def verdict_ok(safety: Dict, liveness: Dict) -> bool:
    """The one-bit summary both harnesses gate on."""
    return bool(
        safety["prefix_agree"] and safety["oracle_agree"]
        and liveness["decided_final"] > 0
        and liveness["advanced_after_heal"]
    )


@dataclasses.dataclass
class ChaosScenario:
    """One seeded chaos run: population shape + fault schedule.

    ``plan.crashes`` / ``plan.partitions`` use member indices; crash
    windows must name honest members (indices >= ``n_forkers`` and not in
    ``adversaries``) and close before ``n_turns`` so the liveness claim
    is testable.

    ``adversaries`` installs active byzantine drivers from
    :mod:`tpu_swirld.adversary`: member index -> ``factory(sim, index)``
    returning an object with ``ask_sync`` / ``ask_events`` endpoints and
    an optional ``step(turn, honest_pks)`` called every turn.  These
    compose with the legacy ``n_forkers`` divergent forkers and with the
    fault plan — byzantine and network faults share one transport.
    ``attack_end`` extends the liveness horizon: decided progress is
    measured after ``max(plan.heal_time(), attack_end)``, so a timed
    attack window counts as a fault the run must recover from.
    """

    n_nodes: int = 5
    n_turns: int = 300
    seed: int = 0
    n_forkers: int = 0
    fork_every: int = 3
    plan: FaultPlan = dataclasses.field(default_factory=FaultPlan)
    checkpoint_every: int = 50
    recovery_pull_rounds: int = 3   # max pull-only sweeps after a restart
    tpu_node_index: Optional[int] = None  # honest member on backend="tpu"
    adversaries: Optional[Dict[int, Callable]] = None  # index -> factory
    attack_end: int = 0             # last turn of the attack window

    def byzantine_indices(self) -> set:
        byz = set(range(self.n_forkers))
        if self.adversaries:
            byz.update(self.adversaries)
        return byz

    def config(self) -> SwirldConfig:
        return SwirldConfig(
            n_members=self.n_nodes, seed=self.seed, quarantine_forkers=True
        )


class ChaosSimulation:
    """Drive one :class:`ChaosScenario` and produce a verdict."""

    def __init__(
        self,
        scenario: ChaosScenario,
        ckpt_dir: str,
        metrics=None,
        tracer=None,
        on_turn: Optional[Callable[[int, "ChaosSimulation"], None]] = None,
        flightrec=None,
        finality: Optional[bool] = None,
    ):
        sc = scenario
        byz = sc.byzantine_indices()
        heal = max(sc.plan.heal_time(), sc.attack_end)
        if heal >= sc.n_turns:
            raise ValueError(
                f"fault schedule ends at t={heal} but the run is only "
                f"{sc.n_turns} turns; liveness-after-heal is untestable"
            )
        for idx, windows in sc.plan.crashes.items():
            if idx in byz:
                raise ValueError("crash windows must name honest members")
            for down, up in windows:
                # down >= 1 so the turn-0 checkpoint exists to restore from
                if not 1 <= down < up:
                    raise ValueError(f"bad crash window {(down, up)}")
        self.scenario = sc
        self.ckpt_dir = ckpt_dir
        os.makedirs(ckpt_dir, exist_ok=True)
        self.metrics = metrics
        self.tracer = tracer
        self.flightrec = flightrec
        # per-node finality trackers default on whenever metrics flow (the
        # histograms land in the same registry the verdict exports from)
        self.finality = bool(metrics) if finality is None else bool(finality)
        self.config = sc.config()
        pop = build_population(
            sc.n_nodes, sc.seed,
            transport_factory=lambda network, want, members, clock:
                FaultyTransport(network, want, sc.plan, members, clock),
        )
        self.rng = pop.rng
        self.keys = pop.keys
        self.members = pop.members
        self.network: Dict[bytes, Callable] = pop.network
        self.network_want: Dict[bytes, Callable] = pop.network_want
        self.clock = pop.clock
        self.transport: FaultyTransport = pop.transport
        self.forkers: List[DivergentForker] = []
        self.adversary_drivers: List = []   # active drivers (adversary.py)
        # honest nodes indexed by MEMBER index (None while crashed)
        self.nodes: Dict[int, Optional[Node]] = {}
        adversaries = sc.adversaries or {}
        for i, (pk, sk) in enumerate(self.keys):
            if i < sc.n_forkers:
                f = DivergentForker(
                    sk, pk, self.members, self.network, self.network_want,
                    self.config, lambda: self.clock[0], self.rng,
                    transport=self.transport,
                )
                self.network[pk] = f.ask_sync
                self.network_want[pk] = f.ask_events
                self.forkers.append(f)
            elif i in adversaries:
                drv = adversaries[i](self, i)
                self.network[pk] = drv.ask_sync
                self.network_want[pk] = drv.ask_events
                self.adversary_drivers.append(drv)
            else:
                self.nodes[i] = self._make_node(i)
        self.on_turn = on_turn
        self.crashes = 0
        self.restarts = 0
        # own-event WAL: the durable log of each member's self-signed
        # events since its last checkpoint (see the module docstring)
        self._wal: Dict[int, List] = {i: [] for i in self.nodes}
        self._decided_at_heal: Optional[int] = None
        self._heal_t = heal

    # ----------------------------------------------------------- plumbing

    def _node_config(self, i: int) -> SwirldConfig:
        if self.scenario.tpu_node_index == i:
            return dataclasses.replace(
                self.config, backend="tpu", block_size=128
            )
        return self.config

    def _make_node(self, i: int) -> Node:
        pk, sk = self.keys[i]
        node = Node(
            sk=sk, pk=pk, network=self.network, members=self.members,
            config=self._node_config(i), clock=lambda: self.clock[0],
            network_want=self.network_want, transport=self.transport,
        )
        attach_obs(
            node, self.metrics, self.tracer, finality=self.finality,
            flightrec=self.flightrec, label=f"n{i}",
        )
        self.network[pk] = node.ask_sync
        self.network_want[pk] = node.ask_events
        return node

    def _ckpt_path(self, i: int) -> str:
        return os.path.join(self.ckpt_dir, f"node-{i}.swck")

    def _crash(self, i: int) -> None:
        """Kill member i: all in-memory state is lost, endpoints torn
        down, and the transport refuses routes until restart."""
        pk = self.members[i]
        self.nodes[i] = None
        self.network.pop(pk, None)
        self.network_want.pop(pk, None)
        self.transport.set_down(pk)
        self.crashes += 1

    def _restore(self, i: int) -> None:
        """Restart member i from its last checkpoint + own-event WAL and
        replay forward: WAL events whose other-parents are not in the
        checkpoint park as orphans; the recovery sweeps' want-list
        round-trips fetch those parents, draining the orphans and moving
        the node's head back to its true pre-crash tip — so new events
        extend the chain instead of equivocating against it."""
        pk, sk = self.keys[i]
        node = load_node(
            self._ckpt_path(i), sk=sk, pk=pk, network=self.network,
            network_want=self.network_want, clock=lambda: self.clock[0],
            transport=self.transport,
        )
        attach_obs(
            node, self.metrics, self.tracer, finality=self.finality,
            flightrec=self.flightrec, label=f"n{i}",
        )
        self.transport.set_up(pk)
        self.network[pk] = node.ask_sync
        self.network_want[pk] = node.ask_events
        self.nodes[i] = node
        self.restarts += 1
        wal_ids: List[bytes] = []
        node._ingest(self._wal[i], wal_ids)
        if wal_ids:
            node.consensus_pass(wal_ids)
        for _ in range(max(0, self.scenario.recovery_pull_rounds)):
            progress = False
            for peer in self.members:
                if peer == pk or peer in self.transport.down:
                    continue
                got = node.pull(peer)
                if got:
                    node.consensus_pass(got)
                    progress = True
            if not progress and not node._orphans:
                break

    def _checkpoint_all(self) -> None:
        for i, node in self.nodes.items():
            if node is not None:
                save_node(self._ckpt_path(i), node)
                # the checkpoint covers everything it ingested; entries a
                # restored node has not re-learned yet stay durable
                self._wal[i] = [
                    ev for ev in self._wal[i] if ev.id not in node.hg
                ]

    def _live_honest(self) -> List[Node]:
        return [n for n in self.nodes.values() if n is not None]

    def _min_decided(self) -> int:
        live = self._live_honest()
        return min(len(n.consensus) for n in live) if live else 0

    # ---------------------------------------------------------------- run

    def run(self) -> Dict:
        sc = self.scenario
        honest_pks = [self.members[i] for i in self.nodes]
        for turn in range(sc.n_turns):
            self.clock[0] = turn
            for idx, windows in sc.plan.crashes.items():
                for down, up in windows:
                    if turn == down and self.nodes.get(idx) is not None:
                        self._crash(idx)
                    elif turn == up and self.nodes.get(idx) is None:
                        self._restore(idx)
            if turn % max(1, sc.checkpoint_every) == 0:
                self._checkpoint_all()
            live = [i for i, n in self.nodes.items() if n is not None]
            if not live:
                continue   # whole-cluster outage window: dead air
            ni = live[self.rng.randrange(len(live))]
            node = self.nodes[ni]
            peers = [pk for pk in self.members if pk != node.pk]
            peer = peers[self.rng.randrange(len(peers))]
            wal = self._wal[ni]
            if wal and node.head != wal[-1].id:
                # restored but its own signing tail is still orphaned
                # (e.g. restarted inside a partition): pull-only turns —
                # signing now would equivocate against the lost tip
                got = node.pull(peer)
                if got:
                    node.consensus_pass(got)
            else:
                prev_head = node.head
                new_ids = node.sync(peer, b"tx:%d:%d" % (ni, turn))
                node.consensus_pass(new_ids)
                if node.head != prev_head:
                    wal.append(node.hg[node.head])
                if self.flightrec is not None:
                    self.flightrec.record_turn(
                        f"n{ni}", turn, decided=len(node.consensus),
                        new=len(new_ids),
                    )
            if sc.n_forkers and turn % max(1, sc.fork_every) == 0:
                for f in self.forkers:
                    f.step(honest_pks)
            for drv in self.adversary_drivers:
                step = getattr(drv, "step", None)
                if step is not None:
                    step(turn, honest_pks)
            if turn == self._heal_t:
                self._decided_at_heal = self._min_decided()
            if self.on_turn is not None:
                self.on_turn(turn, self)
        # any member still down at the end comes back for the verdict
        for idx, node in list(self.nodes.items()):
            if node is None:
                self._restore(idx)
        v = self.verdict()
        v["flightrec_dump"] = self.flightrec_postmortem(v)
        return v

    # ------------------------------------------------------------ verdict

    def decided_frontier(self) -> Dict[str, Dict[str, int]]:
        """Per-node decided state (what a post-mortem must pin: the
        consensus watermark, last committed round, and store size of
        every live honest member at dump time)."""
        out: Dict[str, Dict[str, int]] = {}
        for i, n in sorted(self.nodes.items()):
            if n is None:
                continue
            out[f"n{i}"] = {
                "decided": len(n.consensus),
                "consensus_round": n.consensus_round,
                "events": len(n.hg),
            }
        return out

    def flightrec_postmortem(self, verdict: Dict) -> Optional[str]:
        """Fire the black-box on a red verdict.  Returns the dump path
        (``None`` when the verdict is green, no recorder is attached, or
        the recorder has no ``dump_dir``)."""
        if self.flightrec is None or verdict.get("ok"):
            return None
        return self.flightrec.trigger(
            "verdict_failed",
            detail={
                "safety": verdict.get("safety"),
                "liveness": verdict.get("liveness"),
            },
            decided_frontier=self.decided_frontier(),
            registry=(
                self.metrics.registry if self.metrics is not None else None
            ),
        )

    def oracle_order(self) -> List[bytes]:
        """Fault-free ground truth: a fresh observer replays the union of
        every honest store over a reliable path and recomputes consensus
        from scratch.  By purity of the consensus functions this is the
        order every honest node must have decided a prefix of."""
        union = {}
        for n in self._live_honest():
            union.update(n.hg)
        return oracle_replay(union, self.members, self.config, self.keys[-1])

    def verdict(self) -> Dict:
        nodes = self._live_honest()
        orders = [n.consensus for n in nodes]
        safety = safety_section(orders, self.oracle_order())
        liveness = liveness_section(
            self._min_decided(), self._decided_at_heal, self._heal_t,
        )
        quarantined = sorted(
            {
                self.transport.member_index.get(p, -1)
                for n in nodes
                for p in n.breaker.quarantined()
            }
        )
        return {
            "ok": verdict_ok(safety, liveness),
            "safety": safety,
            "liveness": liveness,
            "faults": dict(self.transport.stats),
            "resilience": {
                "crashes": self.crashes,
                "restarts": self.restarts,
                "retries": sum(n.retries for n in nodes),
                "backoff_total": round(
                    sum(n.backoff_total for n in nodes), 3
                ),
                "bad_replies": sum(n.bad_replies for n in nodes),
                "bad_requests": sum(n.bad_requests for n in nodes),
                "circuit_opens": sum(n.circuit_opens for n in nodes),
                "quarantined_member_indices": quarantined,
                "forks_detected": max(n.forks_detected for n in nodes),
                "equivocations_detected": max(
                    n.equivocations_detected for n in nodes
                ),
                "withholding_suspected": sum(
                    n.withholding_suspected for n in nodes
                ),
                "budget_exhausted": max(n.budget_exhausted for n in nodes),
                "sync_branches_capped": sum(
                    n.sync_branches_capped for n in nodes
                ),
                "orphans_parked": sum(n.orphans_parked for n in nodes),
                "late_witnesses": sum(
                    len(n.late_witnesses) for n in nodes
                ),
                "horizon_violations": sum(
                    n.horizon_violations for n in nodes
                ),
            },
            "scenario": {
                "seed": self.scenario.seed,
                "plan_seed": self.scenario.plan.seed,
                "n_nodes": self.scenario.n_nodes,
                "n_turns": self.scenario.n_turns,
                "n_forkers": self.scenario.n_forkers,
                "adversary_indices": sorted(self.scenario.adversaries or ()),
                "attack_end": self.scenario.attack_end,
            },
        }


def run_chaos(
    scenario: ChaosScenario, ckpt_dir: str, metrics=None, tracer=None,
    flightrec=None,
) -> Dict:
    """Build + run one scenario; returns the verdict dict."""
    return ChaosSimulation(
        scenario, ckpt_dir, metrics=metrics, tracer=tracer,
        flightrec=flightrec,
    ).run()


# ------------------------------------------------- named storm scenarios
#
# The two storm scenarios below pin the PR-4 robustness obligations as
# reproducible JSON verdicts (scripts/chaos_run.py --scenario ...):
#
# - horizon_storm: straggler witnesses fired mid-protocol across a healing
#   partition must land below the committed frontier on the majority side
#   and still leave every engine — live oracle, batch device replay,
#   incremental driver — bit-identical (the deterministic expiry horizon).
# - overflow_storm: witness-table capacity misses (fork-storm slot
#   exhaustion, round-window under-provisioning) must self-heal via the
#   auto-retry instead of fail-stopping, with parity preserved.


def _engines_agree(node, engine: str = "incremental") -> Dict:
    """Cross-engine agreement for one node's full DAG: live oracle state
    vs a cold batch ``run_consensus`` vs a windowed driver replaying the
    same chunked ingest.  ``engine`` picks the windowed driver:
    ``"incremental"`` (:class:`~tpu_swirld.tpu.pipeline.
    IncrementalConsensus`), ``"streaming"`` (:class:`~tpu_swirld.store.
    streaming.StreamingConsensus` — decided rows retire into the slab
    archive and pruned-history references take the widening-rebase path,
    so chaos traffic exercises spill/fetch too), or ``"streaming-mesh"``
    (:class:`~tpu_swirld.parallel.MeshStreamingConsensus` — the same
    streaming replay with the resident window row-sharded over every
    available device, so forked chaos histories hit the halo-exchange
    kernel and sharded widening/fetch paths).  Returns comparison
    booleans (all pure-function replays of the same DAG, so anything but
    bit-equality is a bug)."""
    import functools

    from tpu_swirld.packing import pack_node
    from tpu_swirld.tpu.pipeline import IncrementalConsensus, run_consensus

    if engine == "streaming":
        from tpu_swirld.store.streaming import StreamingConsensus as _Driver
    elif engine == "streaming-mesh":
        import jax

        from tpu_swirld.parallel import MeshStreamingConsensus, make_mesh

        mesh = make_mesh(min(8, len(jax.devices())))
        _Driver = functools.partial(MeshStreamingConsensus, mesh)
    elif engine == "incremental":
        _Driver = IncrementalConsensus
    else:
        raise ValueError(f"unknown engine {engine!r}")

    packed = pack_node(node)
    batch = run_consensus(packed, node.config, block=64)
    oracle_famous = {
        node.idx[w]: node.famous[w]
        for r, ws in node.wit_list.items()
        for w in ws
    }
    batch_oracle = (
        all(
            int(batch.round[i]) == node.round[eid]
            and bool(batch.is_witness[i]) == bool(node.is_witness[eid])
            for i, eid in enumerate(node.order_added)
        )
        and batch.famous == oracle_famous
        and [packed.ids[i] for i in batch.order] == node.consensus
    )
    events = [node.hg[e] for e in node.order_added]
    stake = [node.stake[m] for m in node.members]
    inc = _Driver(
        node.members, stake, node.config, block=64, chunk=64,
        window_bucket=256, prune_min=64,
    )
    for i in range(0, len(events), 64):
        inc.ingest(events[i : i + 64])
    res = inc.result()
    inc_batch = (
        (res.round == batch.round).all()
        and (res.is_witness == batch.is_witness).all()
        and res.famous == batch.famous
        and res.order == batch.order
        and (res.round_received == batch.round_received).all()
        and (res.consensus_ts == batch.consensus_ts).all()
    )
    out = {
        "engine": engine,
        "batch_oracle_parity": bool(batch_oracle),
        "incremental_batch_parity": bool(inc_batch),
        "incremental_rebases": inc.rebases,
    }
    if engine.startswith("streaming"):
        out["store"] = inc.store.stats()
        out["widen_rebases"] = inc.widen_rebases
    if engine == "streaming-mesh":
        out["mesh_devices"] = int(inc.mesh.devices.size)
        out["mesh_repins"] = inc.repins
    return out


def horizon_storm_scenario(seed: int = 1, n_turns: int = 260) -> ChaosScenario:
    """Partition one member into a minority for the middle of the run: it
    keeps signing against its stale view (rounds frozen — a minority can
    never promote), the majority supermajority keeps ordering rounds, and
    at heal the straggler tail floods in below the committed frontier."""
    plan = FaultPlan(
        seed=seed,
        partitions=[
            Partition(start=n_turns // 4, end=(2 * n_turns) // 3, group=(4,))
        ],
    )
    return ChaosScenario(
        n_nodes=5, n_turns=n_turns, seed=seed, n_forkers=0, plan=plan,
        checkpoint_every=50,
    )


def run_horizon_storm(ckpt_dir: str, seed: int = 1, metrics=None,
                      tracer=None, engine: str = "incremental",
                      flightrec=None) -> Dict:
    """Run the straggler-witness scenario and extend the verdict with the
    horizon section: late-witness counts and cross-engine agreement.  The
    old node-local quarantine made exactly this history a documented
    divergence corner (parity suites excluded it with ``assert not
    node.ancient``); the deterministic horizon must decide it
    bit-identically on every node and engine.

    Two straggler sources compose: the partitioned member's own stale
    tail (natural), and a deterministic post-heal injection of forged
    straggler witnesses deep below the majority frontier (the shape an
    amnesiac or equivocating laggard produces) — so the corner fires on
    every run, not just lucky seeds."""
    from tpu_swirld.sim import make_straggler_event

    scenario = horizon_storm_scenario(seed)
    inject_t = scenario.plan.heal_time() + 10
    iso = scenario.plan.partitions[0].group[0]
    injected: List[bytes] = []

    def _fire_stragglers(turn: int, sim: "ChaosSimulation") -> None:
        if turn != inject_t or injected:
            return
        pk, sk = sim.keys[iso]
        target = next(
            n for i, n in sim.nodes.items() if n is not None and i != iso
        )
        try:
            ev = make_straggler_event(target, pk, sk, at_round=1)
        except ValueError:
            return
        new_ids: List[bytes] = []
        target._ingest([ev], new_ids)
        if new_ids:
            target.consensus_pass(new_ids)
            injected.extend(new_ids)

    sim = ChaosSimulation(
        scenario, ckpt_dir, metrics=metrics, tracer=tracer,
        on_turn=_fire_stragglers, flightrec=flightrec,
    )
    verdict = sim.run()
    nodes = sim._live_honest()
    late = sum(len(n.late_witnesses) for n in nodes)
    violations = sum(n.horizon_violations for n in nodes)
    probe = max(nodes, key=lambda n: len(n.hg))
    engines = _engines_agree(probe, engine=engine)
    verdict["horizon"] = {
        "late_witnesses": late,
        "horizon_violations": violations,
        **engines,
    }
    verdict["ok"] = bool(
        verdict["ok"]
        and late > 0                       # the corner actually fired
        and violations == 0
        and engines["batch_oracle_parity"]
        and engines["incremental_batch_parity"]
    )
    # the horizon fold can flip a green run() verdict red — make sure a
    # red verdict still ships its forensic bundle
    if not verdict["ok"] and not verdict.get("flightrec_dump"):
        verdict["flightrec_dump"] = sim.flightrec_postmortem(verdict)
    return verdict


def run_overflow_storm(seed: int = 4, flightrec=None) -> Dict:
    """Device-engine self-healing verdict, two legs:

    - *fork storm*: a heavily equivocating DAG run with a deliberately
      under-provisioned witness-slot capacity (``s_max``) — previously a
      fail-stop ``RuntimeError("witness table overflow")``, now a doubled-
      ``s_max`` auto-retry that must finish with oracle parity;
    - *round clamp*: a deep DAG run with an under-provisioned round window
      (``r_max``) — the chain-derived clamp's failure shape — which must
      retry unclamped at ``config.max_rounds`` and finish with parity.
    """
    from tpu_swirld.config import SwirldConfig
    from tpu_swirld.oracle.node import Node as _Node
    from tpu_swirld.packing import pack_events, pack_node
    from tpu_swirld.sim import generate_gossip_dag, make_simulation
    from tpu_swirld.tpu.pipeline import run_consensus

    def _oracle_parity(packed_dag, result, oracle_node) -> bool:
        """Shared parity predicate for both storm legs (keep in lock-step:
        order AND per-event rounds must match the oracle exactly)."""
        return bool(
            [packed_dag.ids[i] for i in result.order] == oracle_node.consensus
            and all(
                int(result.round[i]) == oracle_node.round[eid]
                for i, eid in enumerate(oracle_node.order_added)
            )
        )

    members, stake, events, keys = generate_gossip_dag(
        8, 500, seed=seed, n_forkers=3, fork_prob=0.4
    )
    packed = pack_events(events, members, stake)
    oracle = _Node(
        sk=keys[0][1], pk=members[0], network={}, members=members,
        clock=lambda: 0, create_genesis=False,
        config=SwirldConfig(n_members=8),
    )
    new_ids = [ev.id for ev in events if oracle.add_event(ev)]
    oracle.consensus_pass(new_ids)
    res_a = run_consensus(
        packed, oracle.config, block=64, s_max=len(members) + 1
    )
    fork_leg = {
        "fork_pairs": int(packed.fork_pairs.shape[0]),
        "overflow_retries": int(res_a.timings.get("overflow_retries", 0)),
        "parity": _oracle_parity(packed, res_a, oracle),
    }

    # rotating-stake population: unequal stakes make the >2/3 witness
    # quorum rotate among weighted subsets round to round.  (A DAG whose
    # max_round NATURALLY exceeds the chain clamp is provably impossible:
    # every promoted round needs witnesses from >2/3 of stake, so some
    # member witnesses — and therefore chains — at least ~2/3 of all
    # rounds, and the visibility echo each promotion needs pushes the
    # longest chain past max_round.  The clamp's failure shape is an
    # under-provisioned explicit r_max, which is what this leg drives.)
    cfg_b = SwirldConfig(n_members=5, stake=(3, 2, 2, 1, 1), seed=seed)
    sim = make_simulation(5, seed=seed, config=cfg_b)
    sim.run(320)
    node = sim.nodes[0]
    packed_b = pack_node(node)
    res_b = run_consensus(packed_b, node.config, block=64, r_max=8)
    clamp_leg = {
        "max_round": int(res_b.max_round),
        "overflow_retries": int(res_b.timings.get("overflow_retries", 0)),
        "parity": _oracle_parity(packed_b, res_b, node),
    }
    ok = bool(
        fork_leg["parity"] and fork_leg["overflow_retries"] >= 1
        and clamp_leg["parity"] and clamp_leg["overflow_retries"] >= 1
        and clamp_leg["max_round"] >= 8
    )
    dump = None
    if flightrec is not None and not ok:
        # no live simulation here — the frontier is the two legs' replay
        # endpoints (oracle watermark and batch order length per leg)
        dump = flightrec.trigger(
            "verdict_failed",
            detail={"fork_storm": fork_leg, "round_clamp": clamp_leg},
            decided_frontier={
                "fork_storm": {"decided": len(oracle.consensus)},
                "round_clamp": {"decided": len(node.consensus)},
            },
        )
    return {
        "ok": ok,
        "fork_storm": fork_leg,
        "round_clamp": clamp_leg,
        "scenario": {"seed": seed, "name": "overflow_storm"},
        "flightrec_dump": dump,
    }


def run_membership_churn(
    ckpt_dir: str, seed: int = 11, flightrec=None,
) -> Dict:
    """Dynamic-membership acceptance storm: an adversary JOINS mid-run,
    mounts an equivocation storm spanning the vote-out epoch boundary,
    and is removed by a decided LEAVE transaction — the "voted out"
    path.  Three phases over one dynamic-membership gossip population:

    1. *admit*: a JOIN tx for a fresh key rides honest gossip, decides,
       and activates; the joiner node comes online mid-run
       (:func:`~tpu_swirld.membership.dynamic.joining_node`), bootstraps
       from gossip, and gains stake at its epoch's activation round.
    2. *attack*: the admitted member mints fork pairs (divergent events
       at equal seq fed to different honest nodes) through the window in
       which the honest members issue the LEAVE tx — so fork pairs
       straddle the vote-out epoch's activation boundary.
    3. *vote-out*: the LEAVE decides, the leaver's stake zeroes at the
       activation round, and the storm loses all voting power: no event
       it creates at or past activation is ever a witness.

    Verdict gates: the join and leave epochs both decided (≥ 3 epochs);
    forks detected by every honest node with the 3f budget silent (one
    forked creator, f = 1); zero-stake witness gating post-activation;
    honest prefix agreement; liveness THROUGH the churn (decisions
    advance after vote-out); all five dynamic engine drivers
    bit-identical on the surviving DAG; and a checkpoint of the densest
    honest node round-trips with its epoch ledger verified.
    """
    from tpu_swirld import crypto as _crypto
    from tpu_swirld.checkpoint import load_node, save_node
    from tpu_swirld.membership.engine import run_all_engines
    from tpu_swirld.membership.sim import make_dynamic_simulation
    from tpu_swirld.membership.txs import join_payload, leave_payload
    from tpu_swirld.oracle.event import Event as _Event

    n = 4
    apk, ask = _crypto.keypair(b"churn-adversary-%d" % seed)
    sim = make_dynamic_simulation(n, seed=seed)
    honest = list(sim.nodes)

    # phase 1: a JOIN for the adversary key rides honest gossip
    sim.tx_schedule[15] = join_payload(apk, 1)
    sim.run(220)
    adv = sim.add_joiner(ask, apk)
    sim.run(120)
    join_epochs = len(honest[0].ledger.epochs)
    joined = apk in honest[0].member_index

    def _mint_fork_pair() -> int:
        """Equivocate: a sibling of the adversary's newest chain event
        (same self-parent, same seq, different payload) is fed straight
        to every honest node — the by_seq fork group forms wherever both
        siblings land."""
        probe = max(honest, key=lambda x: len(x.hg))
        chain = probe.member_events.get(apk, [])
        if len(chain) < 2:
            return 0
        newest = probe.hg[chain[-1]]
        sp, op = newest.p if newest.p else (None, None)
        if sp is None:
            return 0
        sib = _Event(
            d=b"equivocate:%d" % len(chain),
            p=(sp, op),
            t=newest.t + 1,
            c=apk,
        ).signed(ask)
        fed = 0
        for node in honest:
            if sib.id in node.hg or sp not in node.hg or op not in node.hg:
                continue
            if node.add_event(sib):
                node.consensus_pass([sib.id])
                fed += 1
        return fed

    # phase 2+3: the storm runs through the vote-out window — the LEAVE
    # tx decides mid-storm, so fork-pair events land on both sides of
    # the removal epoch's activation round.  The LEAVE is injected by a
    # direct honest sync (not tx_schedule, whose random turn owner could
    # be the adversary itself): honest member 0 votes the attacker out.
    pairs_fed = 0
    for i in range(30):
        sim.run(12)
        pairs_fed += _mint_fork_pair()
        if i == 8:
            sim.clock[0] += 1
            new_ids = honest[0].sync(honest[1].pk, leave_payload(apk))
            honest[0].consensus_pass(new_ids)
    sim.run(150)

    node0 = max(honest, key=lambda x: len(x.consensus))
    epochs = node0.ledger.epochs
    voted_out = (
        len(epochs) > join_epochs
        and node0.ledger.head.stake_of(apk) == 0
    )
    act = epochs[-1].activation_round if voted_out else None

    # witness gating: no adversary event at/past the removal activation
    # round is a witness on any honest node
    gated = True
    post_act_events = 0
    if voted_out:
        for node in honest:
            for eid, w in node.is_witness.items():
                if node.hg[eid].c != apk:
                    continue
                if node.round.get(eid, 0) >= act:
                    post_act_events += 1
                    if w:
                        gated = False

    forks = {
        "pairs_fed": pairs_fed,
        "forks_detected": min(x.forks_detected for x in honest),
        "equivocations_detected": min(
            x.equivocations_detected for x in honest
        ),
        "budget_exhausted": max(x.budget_exhausted for x in honest),
    }

    # safety: honest prefix agreement; liveness: decisions advanced past
    # the vote-out activation
    orders = [x.consensus for x in honest]
    m = min(len(o) for o in orders)
    prefix_agree = all(o[:m] == orders[0][:m] for o in orders)
    decided_at_act = sum(
        1 for x in node0.consensus if node0.round_received[x] < act
    ) if voted_out else 0
    liveness_ok = voted_out and len(node0.consensus) > decided_at_act

    # cross-engine parity on the surviving DAG (fork pairs + 3 epochs)
    events = [node0.hg[e] for e in node0.order_added]
    try:
        results = run_all_engines(
            events, list(node0._genesis_members),
            list(node0._genesis_stake), node0.config, chunk=64,
        )
        engines = {
            "parity": True,
            "decided": len(results["batch"].order),
            "epochs": results["batch"].epochs,
            "restatements": results["batch"].restatements,
            "repacks": [s.to_dict() for s in results["batch"].repacks],
            "archive_epochs_spanned": len({
                e for _, e in results["streaming"].archive_epochs
            }),
            "mesh_repins": [len(p) for p in results["mesh"].shard_pins],
        }
    except AssertionError as exc:
        engines = {"parity": False, "error": str(exc)}

    # checkpoint: the epoch ledger must survive a save/load round trip
    ckpt_path = os.path.join(ckpt_dir, "membership_churn.ckpt")
    save_node(ckpt_path, node0)
    try:
        restored = load_node(ckpt_path, node0.sk, node0.pk, {}, {})
        ckpt = {
            "ok": bool(
                restored.ledger.same_epochs(node0.ledger)
                and restored.consensus == node0.consensus
            ),
            "epochs": len(restored.ledger.epochs),
        }
    except ValueError as exc:
        ckpt = {"ok": False, "error": str(exc)}

    ok = bool(
        joined and voted_out and gated and post_act_events > 0
        and forks["equivocations_detected"] > 0
        and forks["budget_exhausted"] == 0
        and prefix_agree and liveness_ok
        and engines.get("parity") and engines.get("epochs", 0) >= 3
        and ckpt["ok"]
    )
    dump = None
    if flightrec is not None and not ok:
        dump = flightrec.trigger(
            "verdict_failed",
            detail={"membership_churn": {
                "joined": joined, "voted_out": voted_out, "gated": gated,
            }},
            decided_frontier={"decided": len(node0.consensus)},
        )
    return {
        "ok": ok,
        "scenario": {"seed": seed, "name": "membership_churn"},
        "membership": {
            "joined": joined,
            "voted_out": voted_out,
            "epochs": len(epochs),
            "activation_round": act,
            "witness_gating_ok": gated,
            "adversary_events_post_activation": post_act_events,
            "joiner_decided": len(adv.consensus),
        },
        "adversary": {"strategy": "membership_churn", **forks},
        "safety": {"prefix_agree": prefix_agree},
        "liveness": {
            "decided": len(node0.consensus),
            "decided_at_vote_out": decided_at_act,
            "advanced_after_vote_out": liveness_ok,
        },
        "engines": engines,
        "checkpoint": ckpt,
        "flightrec_dump": dump,
    }


def replay_counterexample(doc_or_path, engine: str = "incremental") -> Dict:
    """Ingest a model-checker counterexample document (the JSON emitted
    by ``python -m tpu_swirld.analysis mc --out ...``) into the chaos
    harness: replay the minimized schedule bit-deterministically through
    the real node + transport seam, confirm the recorded violation and
    per-node state digests reproduce exactly, and — for UNMUTATED
    documents, where the consensus core is the shipping code — fold in a
    cross-engine parity row (:func:`_engines_agree`) for the densest
    honest node of the final state, tying the checker's explicit-state
    worlds to the same oracle/device/streaming agreement bar every chaos
    scenario is held to.  Mutated documents skip the parity probe (a
    seeded bug is EXPECTED to diverge) and gate only on replay fidelity.
    """
    from tpu_swirld.analysis.mc import counterexample as _ce

    doc = (
        _ce.load(doc_or_path) if isinstance(doc_or_path, (str, os.PathLike))
        else doc_or_path
    )
    rep = _ce.replay(doc)
    out: Dict = {
        "kind": "mc-replay",
        "mutate": doc["world"].get("mutate"),
        "schedule_len": len(doc["schedule"]),
        "violation": doc.get("violation"),
        "reproduced": rep["reproduced"],
        "digests_match": rep["digests_match"],
        "trace_match": rep["trace_match"],
    }
    ok = bool(rep["reproduced"] and rep["digests_match"] and rep["trace_match"])
    if out["mutate"] is None:
        world, nodes = rep["_world"], rep["_nodes"]
        probe = max(
            (nodes[r] for r in world.honest_roles), key=lambda n: len(n.hg)
        )
        try:
            engines = _engines_agree(probe, engine=engine)
        except Exception as exc:  # device path unavailable -> report, fail
            engines = {"engine": engine, "error": repr(exc)}
            ok = False
        else:
            ok = ok and bool(
                engines["batch_oracle_parity"]
                and engines["incremental_batch_parity"]
            )
        out["engines"] = engines
    out["ok"] = ok
    return out
