"""The batched device consensus pipeline (JAX / XLA).

This is the TPU-native replacement for the oracle's per-event recursion
(``Node.divide_rounds`` / ``decide_fame`` / ``find_order`` — SURVEY.md §2
#6-8, BASELINE.json north star).  It consumes a :class:`~tpu_swirld.packing.
PackedDAG` and produces **bit-identical** ``round`` / ``is_witness`` /
``famous`` / ``(round_received, consensus_ts)`` outputs; the final total
order additionally applies the signature-whitened hash tiebreak, which is a
host-side byte operation (``run_consensus``).

Phase structure (each phase a pure jittable function; ``consensus_arrays``
fuses them into one jit for the end-to-end device step):

1. ``ancestry`` — reflexive-transitive parent closure as a *blockwise*
   boolean matmul: events are processed in topological blocks; each block's
   internal closure is log2(B) squarings of a B×B adjacency (MXU), then one
   (B×B)@(B×N) matmul propagates the external parent rows.  This is the
   "tiled boolean matrix-power reachability" kernel of SURVEY §5.
2. ``forkseen_matrix`` / ``sees_matrix`` — fork-aware visibility.  Fork
   pairs (same creator+seq, packed on host) poison descendants: ``sees(x,y)
   = anc(x,y) & ~forkseen(x, creator(y))``.
3. ``ssm_matrix`` — strongly-sees via the ∃-z member hop: per member m,
   ``hit_m = (S[:, events_m] @ S[events_m, :]) > 0``; stake-weighted count
   of hitting members crosses the strict-2/3 integer threshold.  Exactly
   the oracle's ``strongly_sees`` (∃-z rule).
4. ``rounds_scan`` — ``lax.scan`` over events in topo order carrying the
   round->witness-slot table: round = max(parent rounds) + promotion,
   witness = first-of-creator-in-round.
5. ``fame_scan`` — ``lax.scan`` over rounds carrying the previous round's
   vote matrix: direct votes at distance 1, stake tallies over strongly-
   seen previous-round witnesses (per-creator OR when forks exist), coin
   rounds take the packed signature middle bit; fame is decided by the
   chronologically first supermajority in a non-coin round.
6. ``order_scan`` — per fame-complete round: unique famous witnesses, the
   all-UFW ancestry test for round-received, and a self-parent chain walk
   producing each UFW's earliest-seeing timestamp; consensus timestamp is
   the lower median.

Expiry horizon: the batch pipeline needs no special handling for
"ancient" straggler witnesses — the deterministic rule (expired iff below
the fame-complete frontier of the event's OWN ancestry, which provably
never fires; see :mod:`tpu_swirld.oracle.node`) means every witness simply
registers in scan order, exactly as the oracle registers it in arrival
order.  That shared rule is what makes live-oracle state and batch replays
bit-identical for EVERY history, stragglers included.

Self-healing: the rounds scan reports witness-table overflow as an
``OVF_ROUND | OVF_SLOT`` bitmask and the host orchestrators retry with the
flagged capacity grown (``_healed_capacities``) — a fork storm or a deeper
DAG than the chain-derived ``r_max`` clamp degrades to a slower pass,
never a ``RuntimeError``.

All supermajorities are exact integer tests ``3*amount > 2*total``.  The
device stays int32-pure: int64 timestamps are dense-ranked on the host
(equal timestamps -> equal ranks, so lower-median selection is exact) and
the median *rank* is mapped back to the int64 value after the kernel.  Bool
matmuls run in ``matmul_dtype`` (bfloat16 on TPU — products are 0/1 and the
MXU accumulates in f32, so counts below 2^24 are exact; float32 on CPU) and
threshold at 0.5.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import math
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from tpu_swirld import crypto, obs
from tpu_swirld.config import SwirldConfig, resolve_stream_settings
from tpu_swirld.oracle.node import xor_bytes
from tpu_swirld.packing import PackedDAG, Packer

INT32_MAX = np.iinfo(np.int32).max

# Witness-table overflow bitmask (the rounds scan's self-diagnosis, so the
# host can heal the RIGHT capacity instead of fail-stopping): a witness
# landed outside the retained round window (OVF_ROUND) / a round's witness
# slots were exhausted (OVF_SLOT).
OVF_ROUND = 1
OVF_SLOT = 2


def _maybe_span(o, name: str, **args):
    """A tracer span under the ambient Obs, or a no-op when disabled.

    Stage-granular only — never called per event, so the disabled path
    costs one None check per *stage*."""
    if o is None:
        return contextlib.nullcontext()
    return o.tracer.span(name, **args)


def _record_shapes(o, *, n: int, n_pad: int, statics: Dict) -> None:
    """Pad-waste + static-shape gauges for one pipeline invocation."""
    g = o.registry
    g.gauge("pipeline_events").set(n)
    g.gauge("pipeline_pad_events").set(n_pad - n)
    g.gauge("pipeline_pad_waste_frac").set(
        round((n_pad - n) / max(n_pad, 1), 6)
    )
    g.gauge("pipeline_s_max").set(statics["s_max"])
    g.gauge("pipeline_block").set(statics["block"])
    # pipeline_r_max is set later, once the chain-trimmed effective bound
    # (the one the witness table actually uses) is known


def default_matmul_dtype():
    return jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16


def _bucket(v: int, m: int) -> int:
    """Round up to a multiple of m (recompile hygiene for static shapes)."""
    return ((max(v, 1) + m - 1) // m) * m


class ShapeContractError(ValueError):
    """A device-kernel shape precondition was violated by the caller.

    Raised explicitly (never ``assert`` — asserts vanish under
    ``python -O``, silently disabling the guard in optimized
    deployments; lint rule SW007) and counted in
    :data:`shape_guard_trips` so harnesses can surface how often the
    guard fired."""


#: lifetime count of ShapeContractError raises in this process (a plain
#: module counter: the guard is load-bearing, the count is observability)
shape_guard_trips = 0


def _shape_guard(ok: bool, message: str) -> None:
    if not ok:
        global shape_guard_trips
        shape_guard_trips += 1
        raise ShapeContractError(message)


def _bmm(a: jnp.ndarray, b: jnp.ndarray, dtype) -> jnp.ndarray:
    """Boolean matmul: OR over products of 0/1 values (exact: f32 accum)."""
    return (
        jnp.matmul(
            a.astype(dtype), b.astype(dtype), preferred_element_type=jnp.float32
        )
        > 0.5
    )


# --------------------------------------------------------------- phase 1


def ancestry(parents: jnp.ndarray, *, block: int, matmul_dtype) -> jnp.ndarray:
    """Reflexive-transitive closure of the parent relation.

    ``parents`` int32[N, 2] with -1 for genesis, topologically ordered
    (parents strictly below), N a multiple of ``block``.  Returns bool[N, N]
    with ``anc[i, j]`` = "j is an ancestor of i" (reflexive).
    """
    n = parents.shape[0]
    _shape_guard(
        n % block == 0,
        f"ancestry: N={n} must be padded to a multiple of block={block}",
    )
    n_blocks = n // block
    n_sq = max(1, math.ceil(math.log2(block)))

    eye = jnp.eye(block, dtype=bool)
    jj = jnp.arange(block)

    def body(k, r):
        s = k * block
        pb = lax.dynamic_slice(parents, (s, 0), (block, 2))      # B,2
        local = pb - s                                           # in-block offset
        adj = (local[:, 0:1] == jj[None, :]) | (local[:, 1:2] == jj[None, :])
        lc = adj | eye
        for _ in range(n_sq):                                    # static unroll
            lc = lc | _bmm(lc, lc, matmul_dtype)
        pc = jnp.clip(pb, 0, n - 1)
        ext = pb >= 0                                            # external iff < s,
        ext = ext & (pb < s)                                     # in-block handled by lc
        g = (r[pc[:, 0]] & ext[:, 0:1]) | (r[pc[:, 1]] & ext[:, 1:2])   # B,N
        rows = _bmm(lc, g, matmul_dtype)                         # B,N
        diag = lax.dynamic_slice(rows, (0, s), (block, block)) | lc
        rows = lax.dynamic_update_slice(rows, diag, (0, s))
        return lax.dynamic_update_slice(r, rows, (s, 0))

    r0 = jnp.zeros((n, n), dtype=bool)
    return lax.fori_loop(0, n_blocks, body, r0)


# --------------------------------------------------------------- phase 2


def forkseen_matrix(
    anc: jnp.ndarray, fork_pairs: jnp.ndarray, n_members: int, matmul_dtype
) -> jnp.ndarray:
    """bool[N, M]: does x have a fork pair by member m among its ancestors?

    ``fork_pairs`` int32[G, 3] rows (member, idx_a, idx_b); G may include
    padding rows with member = -1.
    """
    n = anc.shape[0]
    if fork_pairs.shape[0] == 0:
        return jnp.zeros((n, n_members), dtype=bool)
    mcol = fork_pairs[:, 0]
    a = jnp.clip(fork_pairs[:, 1], 0, n - 1)
    b = jnp.clip(fork_pairs[:, 2], 0, n - 1)
    hit = anc[:, a] & anc[:, b] & (mcol >= 0)[None, :]           # N,G
    onehot = mcol[:, None] == jnp.arange(n_members)[None, :]     # G,M
    return _bmm(hit, onehot, matmul_dtype)


def sees_matrix(
    anc: jnp.ndarray, forkseen: jnp.ndarray, creator: jnp.ndarray
) -> jnp.ndarray:
    """Fork-aware visibility: sees(x, y) = anc(x, y) & ~forkseen(x, c(y))."""
    return anc & ~forkseen[:, creator]


# --------------------------------------------------------------- phase 3


def ssm_matrix(
    sees: jnp.ndarray,
    member_table: jnp.ndarray,
    stake: jnp.ndarray,
    tot_stake: int,
    matmul_dtype,
) -> jnp.ndarray:
    """Strongly-sees matrix (∃-z rule): bool[N, N].

    ``ssm[x, w]`` = members holding a strict 2/3 stake supermajority each
    have an event z with sees(x, z) and sees(z, w).
    """
    n = sees.shape[0]
    n_members, k = member_table.shape

    def body(m, acc):
        idx = member_table[m]                        # K
        valid = idx >= 0
        idxc = jnp.clip(idx, 0, n - 1)
        a = sees[:, idxc] & valid[None, :]           # N,K  (x sees z)
        b = sees[idxc, :] & valid[:, None]           # K,N  (z sees w)
        hit = _bmm(a, b, matmul_dtype)               # N,N
        return acc + stake[m] * hit.astype(jnp.int32)

    acc = lax.fori_loop(0, n_members, body, jnp.zeros((n, n), dtype=jnp.int32))
    return 3 * acc > 2 * tot_stake
# --------------------------------------------------------------- phase 4


def rounds_scan(
    parents: jnp.ndarray,
    ssm: jnp.ndarray,
    creator: jnp.ndarray,
    stake: jnp.ndarray,
    tot_stake: int,
    n_valid: jnp.ndarray,
    *,
    r_max: int,
    s_max: int,
    has_forks: bool,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Round assignment + witness registration (topo-order scan).

    Returns (round int32[N], is_witness bool[N], wit_table int32[r_max,
    s_max], wit_count int32[r_max], overflow int32[] — an OVF_ROUND /
    OVF_SLOT bitmask so the orchestrator can retry with the right
    capacity).  Slot order within a round is registration (= topo) order,
    as in the oracle.  (The column-restricted variant runs via
    ``rounds_chunk_stage`` / ``_make_rounds_step`` with a ``col_pos``
    map.)
    """
    step = _make_rounds_step(
        parents, ssm, creator, stake, tot_stake, n_valid,
        jnp.zeros((), dtype=jnp.int32),
        r_max=r_max, s_max=s_max, has_forks=has_forks, col_pos=None,
    )
    n = parents.shape[0]
    carry0 = (
        jnp.zeros((n,), dtype=jnp.int32),
        jnp.zeros((n,), dtype=bool),
        jnp.full((r_max, s_max), -1, dtype=jnp.int32),
        jnp.zeros((r_max,), dtype=jnp.int32),
        jnp.zeros((), dtype=jnp.int32),
    )
    (rnd, wits, tab, cnt, overflow), _ = lax.scan(
        step, carry0, jnp.arange(n)
    )
    return rnd, wits, tab, cnt, overflow


def _make_rounds_step(parents, ssm, creator, stake, tot_stake, n_valid,
                      r_base, *, r_max, s_max, has_forks, col_pos):
    """The shared per-event body of the rounds scan.  Carry:
    (rnd[N], wits[N], wit_table, wit_count, overflow).

    ``rnd`` holds *global* round values; the witness table holds only the
    retained round window — row ``k`` is global round ``r_base + k``
    (``r_base`` a traced scalar so window shifts never retrace).  The
    batch path passes ``r_base = 0``.  ``overflow`` is an int32 OVF_ROUND
    / OVF_SLOT bitmask: an event landing outside the window (including a
    straggler below ``r_base`` in the incremental path) sets OVF_ROUND, a
    full slot row sets OVF_SLOT; the batch orchestrators self-heal by
    growing the flagged capacity, the incremental driver rebases.
    """
    n = parents.shape[0]
    n_members = stake.shape[0]
    marange = jnp.arange(n_members)

    def step(carry, i):
        rnd, wits, tab, cnt, overflow = carry
        p1 = parents[i, 0]
        p2 = parents[i, 1]
        genesis = p1 < 0
        p1c = jnp.maximum(p1, 0)
        p2c = jnp.maximum(p2, 0)
        r0 = jnp.maximum(rnd[p1c], rnd[p2c])
        r0w = r0 - r_base                                   # window row
        r0c = jnp.clip(r0w, 0, r_max - 1)
        widx = tab[r0c]                                     # S
        wvalid = (widx >= 0) & (r0w >= 0) & (r0w < r_max)
        widxc = jnp.clip(widx, 0, n - 1)
        if col_pos is None:
            ss = ssm[i, widxc] & wvalid                     # S
        else:
            wpos = col_pos[widxc]                           # S (-1 = absent)
            ss = (
                ssm[i, jnp.clip(wpos, 0, ssm.shape[1] - 1)]
                & (wpos >= 0)
                & wvalid
            )
        if has_forks:
            wcre = creator[widxc]
            contrib = ((wcre[:, None] == marange[None, :]) & ss[:, None]).any(0)
            amount = jnp.sum(stake * contrib)
        else:
            # no forks packed -> at most one witness per (creator, round)
            amount = jnp.sum(stake[creator[widxc]] * ss)
        promoted = 3 * amount > 2 * tot_stake
        r = jnp.where(genesis, 0, r0 + promoted)
        rw = r - r_base
        is_wit = (genesis | (r > rnd[p1c])) & (i < n_valid)
        overflow = overflow | jnp.where(
            is_wit & ((rw >= r_max) | (rw < 0)), OVF_ROUND, 0
        )
        rc = jnp.clip(rw, 0, r_max - 1)
        slot = cnt[rc]
        overflow = overflow | jnp.where(is_wit & (slot >= s_max), OVF_SLOT, 0)
        do = is_wit & (slot < s_max) & (rw < r_max) & (rw >= 0)
        slotc = jnp.clip(slot, 0, s_max - 1)
        tab = tab.at[rc, slotc].set(jnp.where(do, i, tab[rc, slotc]))
        cnt = cnt.at[rc].add(do.astype(jnp.int32))
        rnd = rnd.at[i].set(jnp.where(i < n_valid, r, 0))
        wits = wits.at[i].set(is_wit)
        return (rnd, wits, tab, cnt, overflow), None

    return step


# --------------------------------------------------------------- phase 5


def fame_scan(
    wit_table: jnp.ndarray,
    sees: jnp.ndarray,
    ssm: jnp.ndarray,
    creator: jnp.ndarray,
    coin: jnp.ndarray,
    stake: jnp.ndarray,
    tot_stake: int,
    coin_period: int,
    matmul_dtype,
    *,
    has_forks: bool,
    col_pos: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Virtual fame voting.  Returns ``(famous, decided_at)``: famous
    int8[r_max*s_max] over witness slots (row-major (round, slot)) — 1
    famous, 0 not, -1 undecided — and decided_at int32[r_max*s_max], the
    (table-local) round index whose tally first decided each slot (-1 for
    undecided slots).  ``decided_at`` lets the incremental driver freeze a
    vote horizon: a decision is final iff no witness later registers in a
    round below it.

    With ``col_pos``, ``ssm`` is column-restricted (every queried column is
    a witness, so the map is total here — guaranteed by the host loop).
    """
    r_max, s_max = wit_table.shape
    n = sees.shape[0]
    n_members = stake.shape[0]
    w_max = r_max * s_max
    # The fast tally multiplies stake values into a float32 matmul; that is
    # exact only while every sum stays below 2^24.  Forks additionally need
    # the per-creator OR.  Otherwise take the int32 per-creator path.
    exact_tally = has_forks or tot_stake >= (1 << 24)

    x_event = wit_table.reshape(-1)                     # W
    x_valid = x_event >= 0
    xe = jnp.clip(x_event, 0, n - 1)
    x_round = jnp.arange(w_max, dtype=jnp.int32) // s_max
    marange = jnp.arange(n_members)

    def step(carry, ry):
        v_prev, famous, dec_at = carry                  # bool[S,W], int8[W]
        y_idx = wit_table[ry]                           # S
        y_valid = y_idx >= 0
        ye = jnp.clip(y_idx, 0, n - 1)
        d = ry - x_round                                # W
        sees_yx = sees[ye][:, xe] & y_valid[:, None] & x_valid[None, :]
        p_idx = wit_table[ry - 1]
        p_valid = p_idx >= 0
        pe = jnp.clip(p_idx, 0, n - 1)
        if col_pos is None:
            ssy = ssm[ye][:, pe]                        # S,S
        else:
            ppos = col_pos[pe]
            ssy = (
                ssm[ye][:, jnp.clip(ppos, 0, ssm.shape[1] - 1)]
                & (ppos >= 0)[None, :]
            )
        ssy = ssy & y_valid[:, None] & p_valid[None, :]
        pcre = creator[pe]                              # S
        pstake = jnp.where(p_valid, stake[pcre], 0)
        if exact_tally:
            # per-creator OR before stake-weighting (forked creators may
            # have several witnesses in round ry-1)
            onehot = (pcre[:, None] == marange[None, :]) & p_valid[:, None]
            w1 = (ssy[:, None, :] & onehot.T[None, :, :]).reshape(
                s_max * n_members, s_max
            )                                           # (S*M),S
            yes_c = _bmm(w1, v_prev, matmul_dtype).reshape(
                s_max, n_members, w_max
            )
            no_c = _bmm(w1, ~v_prev & p_valid[:, None], matmul_dtype).reshape(
                s_max, n_members, w_max
            )
            yes = jnp.sum(yes_c * stake[None, :, None], axis=1)     # S,W int32
            no = jnp.sum(no_c * stake[None, :, None], axis=1)
        else:
            sw = ssy * pstake[None, :]                  # S,S int32
            yes = jnp.matmul(
                sw.astype(jnp.float32),
                v_prev.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            ).astype(jnp.int32)
            no = jnp.matmul(
                sw.astype(jnp.float32),
                (~v_prev & p_valid[:, None]).astype(jnp.float32),
                preferred_element_type=jnp.float32,
            ).astype(jnp.int32)
        v_tally = yes >= no                             # S,W
        super_ = 3 * jnp.maximum(yes, no) > 2 * tot_stake
        is_coin = (d % coin_period) == 0                # W
        coin_y = (coin[ye] > 0)[:, None]                # S,1
        vote = jnp.where(
            (d == 1)[None, :],
            sees_yx,
            jnp.where(is_coin[None, :], jnp.where(super_, v_tally, coin_y), v_tally),
        )
        vote = vote & y_valid[:, None] & x_valid[None, :] & (d >= 1)[None, :]
        eligible = (
            super_
            & y_valid[:, None]
            & (x_valid & (d >= 2) & ~is_coin)[None, :]
        )
        any_dec = eligible.any(0)                       # W
        first_y = jnp.argmax(eligible, axis=0)          # W
        val = v_tally[first_y, jnp.arange(w_max)]
        newly = (famous < 0) & any_dec
        famous = jnp.where(newly, val.astype(jnp.int8), famous)
        dec_at = jnp.where(newly, ry, dec_at)
        return (vote, famous, dec_at), None

    carry0 = (
        jnp.zeros((s_max, w_max), dtype=bool),
        jnp.full((w_max,), -1, dtype=jnp.int8),
        jnp.full((w_max,), -1, dtype=jnp.int32),
    )
    (v_last, famous, dec_at), _ = lax.scan(
        step, carry0, jnp.arange(1, r_max, dtype=jnp.int32)
    )
    return famous, dec_at


# --------------------------------------------------------------- phase 6


def order_scan(
    anc: jnp.ndarray,
    wit_table: jnp.ndarray,
    wit_count: jnp.ndarray,
    famous: jnp.ndarray,
    creator: jnp.ndarray,
    self_parent: jnp.ndarray,
    t_rank: jnp.ndarray,
    max_round: jnp.ndarray,
    n_valid: jnp.ndarray,
    *,
    chain: int,
    received0: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Round-received + consensus timestamp ranks.

    Processes the maximal fame-complete prefix of rounds in ascending
    order; an event is received in the first round whose unique famous
    witnesses all have it as an ancestor; its consensus timestamp is the
    lower median of the UFWs' earliest-seeing self-ancestor timestamps
    (as dense ranks — the host maps ranks back to int64 values).
    Returns (round_received int32[N] (-1 = not received), ts_rank int32[N],
    received bool[N]).

    ``received0`` carries already-received flags from earlier incremental
    passes (those events are skipped; the round indices in the outputs are
    then relative to the carried window's ``r_base``).  ``max_round`` must
    be in the same (local) round frame as the witness table rows.
    """
    r_max, s_max = wit_table.shape
    n = anc.shape[0]
    famous_grid = famous.reshape(r_max, s_max)

    wvalid = wit_table >= 0
    decided = (famous_grid >= 0) | ~wvalid
    complete = decided.all(axis=1) & (
        max_round >= jnp.arange(r_max) + 2
    ) & (wit_count > 0)
    # maximal prefix of fame-complete rounds (cumulative AND)
    prefix = jnp.cumprod(complete.astype(jnp.int32)) > 0

    ev_valid = jnp.arange(n) < n_valid

    def step(carry, r):
        received, rr_out, ts_out = carry
        widx = wit_table[r]
        valid = widx >= 0
        we = jnp.clip(widx, 0, n - 1)
        fam = (famous_grid[r] == 1) & valid             # S
        wcre = creator[we]
        # count famous witnesses per creator via pairwise same-creator sum
        same = (wcre[:, None] == wcre[None, :]) & valid[:, None] & valid[None, :]
        cnt_same = jnp.sum(same & fam[None, :], axis=1)  # S: per slot, count of
        ufw = fam & (cnt_same == 1)                      # famous by same creator
        has = ufw.any()

        # The ancestry test + chain walk + median are by far the scan's
        # dominant cost (O(chain * S * N) gathers); rounds that cannot
        # receive anything — outside the fame-complete prefix, or with no
        # unique famous witness — skip them entirely.  Exact: ``newly``
        # was masked by ``prefix[r] & has`` anyway, so the skipped rounds
        # contributed nothing to the carry.
        def receive_round(c2):
            received, rr_out, ts_out = c2
            anc_rows = anc[we]                           # S,N
            all_see = (anc_rows | ~ufw[:, None]).all(0)  # N
            newly = all_see & ~received & ev_valid

            # earliest-seeing timestamps via self-chain walk (w -> genesis)
            def walk(c3, _):
                cur, tsw = c3
                an = anc[cur]                            # S,N
                tsw = jnp.where(an, t_rank[cur][:, None], tsw)
                nxt = self_parent[cur]
                cur = jnp.where(nxt >= 0, nxt, cur)
                return (cur, tsw), None

            ts0 = jnp.full((s_max, n), INT32_MAX, dtype=jnp.int32)
            (cur, tsw), _ = lax.scan(walk, (we, ts0), None, length=chain)
            tsw = jnp.where(ufw[:, None], tsw, INT32_MAX)  # swirld-lint: disable=SW011 -- masking non-UFW rows TO the sort sentinel is the point: they sort last, and med_i < nv keeps the median strictly below any masked row (the packer bounds live timestamps under INT32_MAX)
            ts_sorted = jnp.sort(tsw, axis=0)            # S,N ascending
            nv = jnp.sum(ufw)
            med_i = jnp.clip((nv - 1) // 2, 0, s_max - 1)
            med = ts_sorted[med_i]                       # N
            return (
                received | newly,
                jnp.where(newly, r, rr_out),
                jnp.where(newly, med, ts_out),
            )

        carry = lax.cond(
            prefix[r] & has, receive_round, lambda c2: c2,
            (received, rr_out, ts_out),
        )
        return carry, None

    carry0 = (
        received0 if received0 is not None else jnp.zeros((n,), dtype=bool),
        jnp.full((n,), -1, dtype=jnp.int32),
        jnp.zeros((n,), dtype=jnp.int32),
    )
    (received, rr_out, ts_out), _ = lax.scan(
        step, carry0, jnp.arange(r_max, dtype=jnp.int32)
    )
    return rr_out, ts_out, received


# ----------------------------------------------------------- fused kernel


def rounds_body(
    parents, creator, stake, fork_pairs, member_table, n_valid, *,
    tot_stake, block, r_max, s_max, has_forks, matmul_dtype_name,
    ssm_fn=None,
):
    """Stage A: ancestry -> sees -> strongly-sees -> rounds/witness scan.

    ``ssm_fn`` overrides the strongly-sees kernel (the FLOP bottleneck) —
    ``tpu_swirld.parallel`` passes the mesh-sharded version.  Jittable.
    """
    dt = jnp.bfloat16 if matmul_dtype_name == "bfloat16" else jnp.float32
    n_members = stake.shape[0]
    anc = ancestry(parents, block=block, matmul_dtype=dt)
    fseen = forkseen_matrix(anc, fork_pairs, n_members, dt)
    sees = sees_matrix(anc, fseen, creator)
    if ssm_fn is None:
        ssm = ssm_matrix(sees, member_table, stake, tot_stake, dt)
    else:
        ssm = ssm_fn(sees, member_table, stake, tot_stake, dt)
    rnd, wits, tab, cnt, overflow = rounds_scan(
        parents, ssm, creator, stake, tot_stake, n_valid,
        r_max=r_max, s_max=s_max, has_forks=has_forks,
    )
    max_round = jnp.max(jnp.where(jnp.arange(rnd.shape[0]) < n_valid, rnd, 0))
    return {
        "anc": anc, "sees": sees, "ssm": ssm, "round": rnd,
        "is_witness": wits, "wit_table": tab, "wit_count": cnt,
        "overflow": overflow, "max_round": max_round,
    }


def fame_order_body(
    anc, sees, ssm, wit_table, wit_count, creator, coin, stake, self_parent,
    t_rank, max_round, n_valid, *,
    tot_stake, coin_period, r_max, s_max, chain, has_forks,
    matmul_dtype_name,
):
    """Stage B: fame fixed point + order extraction over rounds [0, r_max)."""
    dt = jnp.bfloat16 if matmul_dtype_name == "bfloat16" else jnp.float32
    tab = wit_table[:r_max]
    cnt = wit_count[:r_max]
    famous, decided_at = fame_scan(
        tab, sees, ssm, creator, coin, stake, tot_stake, coin_period, dt,
        has_forks=has_forks,
    )
    rr, cts_rank, _received = order_scan(
        anc, tab, cnt, famous, creator, self_parent, t_rank, max_round,
        n_valid, chain=chain,
    )
    return {
        "famous": famous, "fame_decided_at": decided_at,
        "round_received": rr, "consensus_ts_rank": cts_rank,
    }


def consensus_body(
    parents,
    creator,
    t_rank,
    coin,
    stake,
    fork_pairs,
    member_table,
    n_valid,
    *,
    tot_stake: int,
    coin_period: int,
    block: int,
    r_max: int,
    s_max: int,
    chain: int,
    has_forks: bool,
    matmul_dtype_name: str,
    ssm_fn=None,
):
    """End-to-end device consensus: packed arrays -> all consensus outputs.

    Composes :func:`rounds_body` + :func:`fame_order_body` in one trace —
    the fused single-jit form used by the graft entry and the mesh path.
    ``run_consensus`` instead runs the two stages as separate jits so the
    second can be re-bound with a tight ``r_max``.
    """
    a = rounds_body(
        parents, creator, stake, fork_pairs, member_table, n_valid,
        tot_stake=tot_stake, block=block, r_max=r_max, s_max=s_max,
        has_forks=has_forks, matmul_dtype_name=matmul_dtype_name,
        ssm_fn=ssm_fn,
    )
    b = fame_order_body(
        a["anc"], a["sees"], a["ssm"], a["wit_table"], a["wit_count"],
        creator, coin, stake, parents[:, 0], t_rank, a["max_round"], n_valid,
        tot_stake=tot_stake, coin_period=coin_period, r_max=r_max,
        s_max=s_max, chain=chain, has_forks=has_forks,
        matmul_dtype_name=matmul_dtype_name,
    )
    return {
        "round": a["round"],
        "is_witness": a["is_witness"],
        "wit_table": a["wit_table"],
        "wit_count": a["wit_count"],
        "overflow": a["overflow"],
        "max_round": a["max_round"],
        **b,
    }


consensus_arrays = functools.partial(
    jax.jit,
    static_argnames=(
        "tot_stake",
        "coin_period",
        "block",
        "r_max",
        "s_max",
        "chain",
        "has_forks",
        "matmul_dtype_name",
    ),
)(consensus_body)

rounds_stage = functools.partial(
    jax.jit,
    static_argnames=(
        "tot_stake", "block", "r_max", "s_max", "has_forks",
        "matmul_dtype_name",
    ),
)(rounds_body)


# --- column-restricted strongly-sees path (default single-host execution):
# visibility once, then an iterated {ssm columns -> rounds scan} loop on the
# host until every registered witness has a column (exactness certificate),
# then fame/order with the position-mapped restricted matrix.


@functools.partial(
    jax.jit, static_argnames=("n_members", "block", "matmul_dtype_name")
)
def visibility_stage(parents, creator, fork_pairs, *, n_members, block,
                     matmul_dtype_name):
    dt = jnp.bfloat16 if matmul_dtype_name == "bfloat16" else jnp.float32
    anc = ancestry(parents, block=block, matmul_dtype=dt)
    fseen = forkseen_matrix(anc, fork_pairs, n_members, dt)
    sees = sees_matrix(anc, fseen, creator)
    return anc, sees


@functools.partial(jax.jit, static_argnames=("block", "matmul_dtype_name"))
def ancestry_stage(parents, *, block, matmul_dtype_name):
    """Ancestry only — the fork-free visibility fast path: with no fork
    pairs packed, ``sees == anc`` (a pair can only exist once its SECOND
    member is packed, and nothing already packed can descend from it), so
    the sees slab is an *alias* of the ancestry slab and is neither
    computed nor stored."""
    dt = jnp.bfloat16 if matmul_dtype_name == "bfloat16" else jnp.float32
    return ancestry(parents, block=block, matmul_dtype=dt)


@functools.partial(
    jax.jit,
    static_argnames=("rows", "tot_stake", "matmul_dtype_name"),
)
def ssm_block_stage(sees, member_table, stake, cols, row0, *, rows,
                    tot_stake, matmul_dtype_name):
    """Strongly-sees block for window rows ``[row0, row0 + rows)`` against
    the column events ``cols``, gathered **directly from the sees slab**:
    per member one (rows, K) @ (K, C) ∃-z hop, int32 stake tally,
    strict-2/3 threshold.

    This is the single strongly-sees kernel of the windowed drivers — the
    row-extension pass (new rows × every live column) and the witness-
    column adds (suffix rows × new columns) are the same computation at
    different offsets, so one kernel serves both and the old per-member
    gather slabs (``a3``/``b3``, ~2×M·W·K resident bools) no longer exist:
    the gathers here read tiles of the one sees slab the store budgets.

    Callers exploit structure to keep ``rows``/``C`` tight: rows *below* a
    witness column can never strongly-see it (z would need to be both
    above the row and below the column), so column adds pass only the
    suffix ``[min(cols), hi)``, and the untouched slab region is already
    the exact value (zero).
    """
    dt = jnp.bfloat16 if matmul_dtype_name == "bfloat16" else jnp.float32
    n = sees.shape[0]
    n_members, k = member_table.shape
    idx = member_table.reshape(-1)
    valid = idx >= 0
    idxc = jnp.clip(idx, 0, n - 1)
    colsc = jnp.clip(cols, 0, n - 1)
    col_valid = cols >= 0
    sees_rows = lax.dynamic_slice(sees, (row0, 0), (rows, n))
    a_flat = sees_rows[:, idxc] & valid[None, :]             # rows,M*K
    b_flat = (
        sees[idxc[:, None], colsc[None, :]]
        & valid[:, None] & col_valid[None, :]
    )                                                        # M*K,C
    if k == 1 and tot_stake < (1 << 24):
        # one member row each: the per-member ∃-z indicator IS the 0/1
        # product, so the whole stake tally collapses into a single
        # (rows, M) @ (M, C) matmul with stake folded into the b-side —
        # exact in f32 while the tally stays below 2^24 (same bound the
        # fame tally relies on), and it replaces M accumulator sweeps
        # over the (rows, C) block with one GEMM.
        acc = jnp.matmul(
            a_flat.astype(jnp.float32),
            b_flat.astype(jnp.float32) * stake[:, None].astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ).astype(jnp.int32)
    else:
        a_r3 = a_flat.reshape(rows, n_members, k).transpose(1, 0, 2)
        b_cols = b_flat.reshape(n_members, k, cols.shape[0])

        def body(m, acc):                   # per-member hop; the (rows, C)
            hit = _bmm(a_r3[m], b_cols[m], dt)  # tally stays in the block
            return acc + stake[m] * hit.astype(jnp.int32)

        acc = lax.fori_loop(
            0, n_members, body,
            jnp.zeros((rows, cols.shape[0]), dtype=jnp.int32),
        )
    return (3 * acc > 2 * tot_stake) & col_valid[None, :]


@functools.partial(jax.jit, donate_argnums=(0,))
def update_block_stage(ssm_c, part, row0, col0):
    """Write one computed block into the donated column store."""
    return lax.dynamic_update_slice(ssm_c, part, (row0, col0))


@functools.partial(jax.jit, static_argnames=("rows",))
def ssm_gather_rows_stage(sees, member_table, row0, *, rows):
    """The a-side gather of :func:`ssm_block_stage` alone: per-member
    "x sees z" rows for window rows ``[row0, row0 + rows)``.  The sees
    slab is frozen between a pass's extension and its prune, so the
    incremental driver gathers this ONCE per pass and reuses it across
    every witness-column add of the pass (the gather, not the matmul,
    dominates small column batches)."""
    n = sees.shape[0]
    n_members, k = member_table.shape
    idx = member_table.reshape(-1)
    valid = idx >= 0
    idxc = jnp.clip(idx, 0, n - 1)
    sees_rows = lax.dynamic_slice(sees, (row0, 0), (rows, n))
    return (
        (sees_rows[:, idxc] & valid[None, :])
        .reshape(rows, n_members, k).transpose(1, 0, 2)
    )                                                        # M,rows,K


@functools.partial(
    jax.jit, static_argnames=("rows", "tot_stake", "matmul_dtype_name")
)
def ssm_block_from_rows_stage(a_r3, sees, member_table, stake, cols,
                              row_off, *, rows, tot_stake,
                              matmul_dtype_name):
    """:func:`ssm_block_stage` resumed from a pre-gathered a-side
    (:func:`ssm_gather_rows_stage`): b-side gather + member hops only,
    over the cached rows ``[row_off, row_off + rows)`` (the caller's
    suffix cut — the slice fuses into the member loop, nothing
    re-materializes)."""
    dt = jnp.bfloat16 if matmul_dtype_name == "bfloat16" else jnp.float32
    n = sees.shape[0]
    n_members, k = member_table.shape
    idx = member_table.reshape(-1)
    valid = idx >= 0
    idxc = jnp.clip(idx, 0, n - 1)
    colsc = jnp.clip(cols, 0, n - 1)
    col_valid = cols >= 0
    b_cols = (
        sees[idxc[:, None], colsc[None, :]]
        & valid[:, None] & col_valid[None, :]
    ).reshape(n_members, k, cols.shape[0])
    if k == 1 and tot_stake < (1 << 24):
        # fused single-GEMM stake tally (see ssm_block_stage): with one
        # gathered row per member the ∃-z hop is the 0/1 product itself
        a2 = lax.dynamic_slice(
            a_r3, (0, row_off, 0), (n_members, rows, 1)
        ).reshape(n_members, rows)
        acc = jnp.matmul(
            a2.T.astype(jnp.float32),
            b_cols.reshape(n_members, cols.shape[0]).astype(jnp.float32)
            * stake[:, None].astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ).astype(jnp.int32)
        return (3 * acc > 2 * tot_stake) & col_valid[None, :]

    def body(m, acc):
        a_m = lax.dynamic_slice(a_r3[m], (row_off, 0), (rows, k))
        hit = _bmm(a_m, b_cols[m], dt)
        return acc + stake[m] * hit.astype(jnp.int32)

    acc = lax.fori_loop(
        0, n_members, body,
        jnp.zeros((rows, cols.shape[0]), dtype=jnp.int32),
    )
    return (3 * acc > 2 * tot_stake) & col_valid[None, :]


def _suffix_rows(row_hi: int, row_lo: int, cap: int):
    """Pick the static suffix-row count for an ssm block: the smallest
    power-of-two ≥ 256 covering ``[row_lo, row_hi)``, clamped to ``cap``
    — a small, session-bounded shape family, so the jit cache stays warm.
    Returns ``(row0, rows)`` with ``row0 ≤ row_lo``."""
    need = max(row_hi - row_lo, 1)
    rows = 256
    while rows < need:
        rows *= 2
    rows = min(rows, cap)
    return max(0, row_hi - rows), rows


@functools.partial(
    jax.jit,
    static_argnames=("tot_stake", "r_max", "s_max", "has_forks", "chunk"),
)
def rounds_chunk_stage(parents, ssm_c, col_pos, creator, stake, n_valid,
                       rnd, wits, tab, cnt, overflow, start, r_base, *,
                       tot_stake, r_max, s_max, has_forks, chunk):
    """One chunk of the rounds scan: events [start, start+chunk) resume
    from the carried (rnd, wits, tab, cnt, overflow) state.  Shares the
    per-event body with rounds_scan — used by the incremental
    column-restricted path.  ``r_base`` (traced) maps global rounds to
    witness-table rows (0 on the batch path)."""
    step = _make_rounds_step(
        parents, ssm_c, creator, stake, tot_stake, n_valid, r_base,
        r_max=r_max, s_max=s_max, has_forks=has_forks, col_pos=col_pos,
    )
    carry0 = (rnd, wits, tab, cnt, overflow)
    (rnd, wits, tab, cnt, overflow), _ = lax.scan(
        step, carry0, start + jnp.arange(chunk)
    )
    return rnd, wits, tab, cnt, overflow


@functools.partial(
    jax.jit,
    static_argnames=(
        "tot_stake", "r_max", "s_max", "has_forks", "chunk", "k_chunks",
    ),
    donate_argnums=(6, 7, 8, 9, 10),
)
def rounds_span_stage(parents, ssm_c, col_pos, creator, stake, n_valid,
                      rnd, wits, tab, cnt, overflow, start, r_base, *,
                      tot_stake, r_max, s_max, has_forks, chunk, k_chunks):
    """``k_chunks`` packed chunks of the rounds scan in ONE dispatch —
    the fused megakernel.  Same per-event body as rounds_chunk_stage,
    scan length ``chunk * k_chunks`` (one compiled body either way; the
    trip count is static).  The carry slabs (rnd/wits/tab/cnt/overflow,
    positions 6-10) are donated: callers re-upload the host-mirror carry
    before every probe, so the witness-column fixpoint retry never reads
    a buffer this dispatch consumed."""
    step = _make_rounds_step(
        parents, ssm_c, creator, stake, tot_stake, n_valid, r_base,
        r_max=r_max, s_max=s_max, has_forks=has_forks, col_pos=col_pos,
    )
    carry0 = (rnd, wits, tab, cnt, overflow)
    (rnd, wits, tab, cnt, overflow), _ = lax.scan(
        step, carry0, start + jnp.arange(chunk * k_chunks)
    )
    return rnd, wits, tab, cnt, overflow


@functools.partial(
    jax.jit,
    static_argnames=(
        "tot_stake", "coin_period", "r_max", "s_max", "chain", "has_forks",
        "matmul_dtype_name",
    ),
)
def fame_order_cols_stage(
    anc, sees, ssm_c, col_pos, wit_table, wit_count, creator, coin, stake,
    self_parent, t_rank, max_round, n_valid, *,
    tot_stake, coin_period, r_max, s_max, chain, has_forks,
    matmul_dtype_name,
):
    dt = jnp.bfloat16 if matmul_dtype_name == "bfloat16" else jnp.float32
    tab = wit_table[:r_max]
    cnt = wit_count[:r_max]
    famous, decided_at = fame_scan(
        tab, sees, ssm_c, creator, coin, stake, tot_stake, coin_period, dt,
        has_forks=has_forks, col_pos=col_pos,
    )
    rr, cts_rank, _received = order_scan(
        anc, tab, cnt, famous, creator, self_parent, t_rank, max_round,
        n_valid, chain=chain,
    )
    return {
        "famous": famous, "fame_decided_at": decided_at,
        "round_received": rr, "consensus_ts_rank": cts_rank,
    }

_pallas_rounds_stages = {}


def rounds_stage_pallas(interpret: bool):
    """rounds_stage with the strongly-sees phase as the Pallas kernel."""
    fn = _pallas_rounds_stages.get(interpret)
    if fn is None:
        from tpu_swirld.tpu.pallas_kernels import make_ssm_fn

        fn = functools.partial(
            jax.jit,
            static_argnames=(
                "tot_stake", "block", "r_max", "s_max", "has_forks",
                "matmul_dtype_name",
            ),
        )(functools.partial(rounds_body, ssm_fn=make_ssm_fn(interpret=interpret)))
        _pallas_rounds_stages[interpret] = fn
    return fn

fame_order_stage = functools.partial(
    jax.jit,
    static_argnames=(
        "tot_stake", "coin_period", "r_max", "s_max", "chain", "has_forks",
        "matmul_dtype_name",
    ),
)(fame_order_body)


# ------------------------------------------------------- host orchestration


@dataclasses.dataclass
class ConsensusResult:
    """Host-side view of the device outputs (indices into the PackedDAG)."""

    n: int
    round: np.ndarray            # int32[n]
    is_witness: np.ndarray       # bool[n]
    famous: Dict[int, Optional[bool]]   # witness idx -> fame (None undecided)
    round_received: np.ndarray   # int32[n] (-1 not received)
    consensus_ts: np.ndarray     # int64[n]
    order: List[int]             # final total order (packed indices)
    max_round: int
    timings: Dict[str, float] = dataclasses.field(default_factory=dict)


def _pad_packed(packed: PackedDAG, block: int):
    n = packed.n
    n_pad = ((n + block - 1) // block) * block
    pad = n_pad - n

    def padi(a, fill):
        if pad == 0:
            return a
        shape = (pad,) + a.shape[1:]
        return np.concatenate([a, np.full(shape, fill, a.dtype)], axis=0)

    parents = padi(packed.parents, -1)
    creator = padi(packed.creator, 0)
    seq = padi(packed.seq, 0)
    t = padi(packed.t, 0)
    coin = padi(packed.coin, 0)
    return n_pad, parents, creator, seq, t, coin


def prepare_inputs(
    packed: PackedDAG,
    config: Optional[SwirldConfig] = None,
    *,
    block: int = 128,
    r_max: Optional[int] = None,
    s_max: Optional[int] = None,
    matmul_dtype_name: Optional[str] = None,
):
    """Host prep shared by :func:`run_consensus` and the graft entry:
    block padding, dense timestamp ranks, and the static shape parameters.

    Returns ``(arrays, statics, ts_unique)`` where ``arrays`` holds the
    numpy kernel inputs (keys match the kernel's positional order:
    parents, creator, t_rank, coin, stake, fork_pairs, member_table,
    n_valid) and ``statics`` the keyword shape parameters.
    """
    config = config or SwirldConfig(n_members=packed.n_members)
    if matmul_dtype_name is None:
        matmul_dtype_name = (
            "float32" if jax.default_backend() == "cpu" else "bfloat16"
        )
    n = packed.n
    _n_pad, parents, creator, _seq, t, coin = _pad_packed(packed, block)
    extras = (
        len(set(packed.fork_pairs[:, 2].tolist()))
        if len(packed.fork_pairs)
        else 0
    )
    if s_max is None:
        s_max = packed.n_members + extras + 1
    if r_max is None:
        r_max = int(config.max_rounds)
    chain = int(packed.seq.max()) + 1 if n else 1
    # dense-rank timestamps so the device stays int32-pure (see module doc)
    ts_unique, t_rank = np.unique(t, return_inverse=True)
    t_rank = t_rank.astype(np.int32).reshape(t.shape)
    arrays = {
        "parents": parents,
        "creator": creator,
        "t_rank": t_rank,
        "coin": coin,
        "stake": packed.stake,
        "fork_pairs": packed.fork_pairs,
        "member_table": packed.member_table,
        "n_valid": np.int32(n),
    }
    statics = {
        "tot_stake": int(packed.stake.sum()),
        "coin_period": config.coin_period,
        "block": block,
        "r_max": r_max,
        "s_max": s_max,
        "chain": chain,
        "has_forks": bool(len(packed.fork_pairs)),
        "matmul_dtype_name": matmul_dtype_name,
    }
    return arrays, statics, ts_unique


def _healed_capacities(ovf: int, *, r_eff: int, r_cap: int, s_eff: int,
                       s_cap: int) -> Tuple[int, int]:
    """Translate a rounds-scan overflow bitmask into grown capacities.

    The self-healing contract (no fail-stop on recoverable capacity
    misses): OVF_ROUND unclamps the witness-table round window straight to
    ``r_cap`` (``config.max_rounds`` — the chain-derived clamp is a
    heuristic, not a theorem the pipeline should die on), OVF_SLOT doubles
    the per-round slot capacity (power-of-two growth keeps the static
    shapes on the existing bucket discipline).  Raises the *corrected*
    error — naming the capacity that is genuinely exhausted and the knob
    that raises it — only when the flagged capacity is already at its hard
    bound.
    """
    r_new, s_new = r_eff, s_eff
    if ovf & OVF_ROUND:
        if r_eff >= r_cap:
            raise RuntimeError(
                f"consensus rounds exceed the round-window capacity "
                f"{r_cap} (the larger of config.max_rounds and any "
                f"explicit r_max); raise SwirldConfig.max_rounds beyond "
                f"{r_cap}"
            )
        r_new = r_cap
    if ovf & OVF_SLOT:
        if s_eff >= s_cap:
            raise RuntimeError(
                f"witness slots per round exceed the padded event count "
                f"({s_cap}) — impossible for a valid DAG; this indicates "
                "packing corruption"
            )
        s_new = min(max(2 * s_eff, 1), s_cap)
    if (r_new, s_new) == (r_eff, s_eff):
        raise RuntimeError(f"unhealable overflow mask {ovf}")
    o = obs.current()
    if o is not None:
        o.registry.counter("pipeline_overflow_retries_total").inc()
        o.registry.gauge("pipeline_r_max").set(r_new)
        o.registry.gauge("pipeline_s_max").set(s_new)
    return r_new, s_new


def run_consensus(
    packed: PackedDAG,
    config: Optional[SwirldConfig] = None,
    *,
    block: int = 128,
    r_max: Optional[int] = None,
    s_max: Optional[int] = None,
    matmul_dtype_name: Optional[str] = None,
    mesh=None,
    use_pallas_ssm: bool = False,
    ssm_mode: Optional[str] = None,
) -> ConsensusResult:
    """Run the full pipeline on a packed DAG and extract the final order.

    The device computes everything except the tiebreak hash; the host
    applies the oracle's exact sort key (round received, consensus ts,
    BLAKE2b(whiten || id)) to produce the total order.  With ``mesh`` (a
    1-D member-axis ``jax.sharding.Mesh``), the strongly-sees phase is
    sharded over the mesh with psum stake aggregation
    (:mod:`tpu_swirld.parallel`).
    """
    arrays, statics, ts_unique = prepare_inputs(
        packed, config, block=block, r_max=r_max, s_max=s_max,
        matmul_dtype_name=matmul_dtype_name,
    )
    config = config or SwirldConfig(n_members=packed.n_members)
    n = packed.n
    o = obs.current()
    if o is not None:
        _record_shapes(
            o, n=n, n_pad=arrays["parents"].shape[0], statics=statics
        )
    parents, creator, t_rank, coin = (
        arrays["parents"], arrays["creator"], arrays["t_rank"], arrays["coin"]
    )
    member_table, stake = arrays["member_table"], arrays["stake"]
    r_max, s_max = statics["r_max"], statics["s_max"]
    chain = statics["chain"]
    tot = statics["tot_stake"]
    matmul_dtype_name = statics["matmul_dtype_name"]
    if ssm_mode not in (None, "columns", "full"):
        raise ValueError(f"unknown ssm_mode {ssm_mode!r}")
    if mesh is not None and use_pallas_ssm:
        raise NotImplementedError(
            "use_pallas_ssm is not yet routed through the sharded (mesh) "
            "path; run one or the other"
        )
    if ssm_mode == "columns" and (mesh is not None or use_pallas_ssm):
        raise NotImplementedError(
            "ssm_mode='columns' is not routed through the mesh/pallas "
            "paths yet; those run the full-matrix kernel"
        )
    if ssm_mode is None:
        # auto: column-restricted on the plain single-host path, full
        # matrix for the fused mesh / pallas kernels
        ssm_mode = "full" if (mesh is not None or use_pallas_ssm) else "columns"
    if mesh is not None:
        from tpu_swirld.parallel import consensus_fn_for_mesh, pad_members

        member_table, stake = pad_members(
            member_table, stake, mesh.devices.size
        )
        kernel = consensus_fn_for_mesh(mesh)
        if o is not None:
            o.registry.gauge("mesh_devices").set(int(mesh.devices.size))
        # the longest self-chain bounds max_round for honest-shaped DAGs;
        # use it as the witness-table clamp, backed by the self-healing
        # retry (an under-provisioned table grows, never crashes)
        r_eff = min(r_max, _bucket(chain + 1, 32))
        r_cap = max(int(config.max_rounds), r_max)
        if o is not None:
            o.registry.gauge("pipeline_r_max").set(r_eff)
        t_dev0 = time.perf_counter()
        retries = 0
        while True:
            out = obs.stage_call(
                "pipeline.mesh_consensus",
                kernel,
                jnp.asarray(parents),
                jnp.asarray(creator),
                jnp.asarray(t_rank),
                jnp.asarray(coin),
                jnp.asarray(stake),
                jnp.asarray(packed.fork_pairs),
                jnp.asarray(member_table),
                jnp.asarray(n, dtype=jnp.int32),
                tot_stake=tot,
                coin_period=config.coin_period,
                block=block,
                r_max=r_eff,
                s_max=s_max,
                chain=chain,
                has_forks=bool(len(packed.fork_pairs)),
                matmul_dtype_name=matmul_dtype_name,
            )
            out = jax.tree.map(np.asarray, out)  # blocks on device completion
            ovf = int(out["overflow"])
            if not ovf:
                break
            r_eff, s_max = _healed_capacities(
                ovf, r_eff=r_eff, r_cap=r_cap, s_eff=s_max,
                s_cap=parents.shape[0],
            )
            retries += 1
        t_device = time.perf_counter() - t_dev0
        t_fin0 = time.perf_counter()
        with _maybe_span(o, "pipeline.finalize"):
            result = finalize_order(packed, out, ts_unique)
        result.timings = {
            "device_and_dispatch": round(t_device, 6),
            "finalize_host": round(time.perf_counter() - t_fin0, 6),
            "overflow_retries": retries,
        }
        return result

    # single-host path: two stages with a tight fame/order r_max.  The
    # longest self-chain bounds max_round for honest-shaped DAGs (a
    # member's round rises at most once per own event); the clamp is a
    # recompile-hygiene heuristic backed by the self-healing retry, so an
    # under-provisioned r_max or s_max grows instead of fail-stopping.
    r_rounds = min(r_max, _bucket(chain + 1, 32))
    r_cap = max(int(config.max_rounds), r_max)
    if o is not None:
        o.registry.gauge("pipeline_r_max").set(r_rounds)
    if ssm_mode == "columns" and not use_pallas_ssm:
        return _run_consensus_columns(
            packed, config, parents, creator, t_rank, coin, stake,
            member_table, ts_unique, n=n, tot=tot, block=block,
            r_rounds=r_rounds, r_cap=r_cap, s_max=s_max, chain=chain,
            matmul_dtype_name=matmul_dtype_name,
        )
    stage_a_fn = rounds_stage
    if use_pallas_ssm:
        from tpu_swirld.tpu.pallas_kernels import resolve_interpret

        stage_a_fn = rounds_stage_pallas(interpret=resolve_interpret())
    t_dev0 = time.perf_counter()
    retries = 0
    while True:
        stage_a = obs.stage_call(
            "pipeline.rounds_stage",
            stage_a_fn,
            jnp.asarray(parents),
            jnp.asarray(creator),
            jnp.asarray(stake),
            jnp.asarray(packed.fork_pairs),
            jnp.asarray(member_table),
            jnp.asarray(n, dtype=jnp.int32),
            tot_stake=tot,
            block=block,
            r_max=r_rounds,
            s_max=s_max,
            has_forks=bool(len(packed.fork_pairs)),
            matmul_dtype_name=matmul_dtype_name,
        )
        ovf = int(np.asarray(stage_a["overflow"]))
        if not ovf:
            break
        r_rounds, s_max = _healed_capacities(
            ovf, r_eff=r_rounds, r_cap=r_cap, s_eff=s_max,
            s_cap=parents.shape[0],
        )
        retries += 1
    max_round = int(stage_a["max_round"])     # device -> host scalar
    r_tight = min(r_rounds, _bucket(max_round + 3, 8))
    stage_b = obs.stage_call(
        "pipeline.fame_order_stage",
        fame_order_stage,
        stage_a["anc"],
        stage_a["sees"],
        stage_a["ssm"],
        stage_a["wit_table"],
        stage_a["wit_count"],
        jnp.asarray(creator),
        jnp.asarray(coin),
        jnp.asarray(stake),
        jnp.asarray(parents[:, 0]),
        jnp.asarray(t_rank),
        stage_a["max_round"],
        jnp.asarray(n, dtype=jnp.int32),
        tot_stake=tot,
        coin_period=config.coin_period,
        r_max=r_tight,
        s_max=s_max,
        chain=chain,
        has_forks=bool(len(packed.fork_pairs)),
        matmul_dtype_name=matmul_dtype_name,
    )
    out = {
        "round": stage_a["round"],
        "is_witness": stage_a["is_witness"],
        "wit_table": stage_a["wit_table"][:r_tight],
        "wit_count": stage_a["wit_count"][:r_tight],
        "max_round": stage_a["max_round"],
        **stage_b,
    }
    out = jax.tree.map(np.asarray, out)       # blocks on device completion
    t_device = time.perf_counter() - t_dev0
    t_fin0 = time.perf_counter()
    with _maybe_span(o, "pipeline.finalize"):
        result = finalize_order(packed, out, ts_unique)
    result.timings = {
        "device_and_dispatch": round(t_device, 6),
        "finalize_host": round(time.perf_counter() - t_fin0, 6),
        "overflow_retries": retries,
    }
    return result


def _run_consensus_columns(
    packed, config, parents, creator, t_rank, coin, stake, member_table,
    ts_unique, *, n, tot, block, r_rounds, r_cap, s_max, chain,
    matmul_dtype_name,
):
    """Column-restricted strongly-sees execution (the default path) —
    :func:`_columns_pass` plus host order extraction and timings."""
    o = obs.current()
    t_dev0 = time.perf_counter()
    out, aux = _columns_pass(
        packed, config, parents, creator, t_rank, coin, stake, member_table,
        n=n, tot=tot, block=block, r_rounds=r_rounds, r_cap=r_cap,
        s_max=s_max, chain=chain, matmul_dtype_name=matmul_dtype_name,
    )
    t_device = time.perf_counter() - t_dev0
    t_fin0 = time.perf_counter()
    with _maybe_span(o, "pipeline.finalize"):
        result = finalize_order(packed, out, ts_unique)
    if o is not None:
        o.registry.counter("pipeline_ssm_columns_total").inc(aux["n_cols"])
        o.registry.counter("pipeline_chunk_scans_total").inc(aux["n_scans"])
    result.timings = {
        "device_and_dispatch": round(t_device, 6),
        "finalize_host": round(time.perf_counter() - t_fin0, 6),
        "ssm_columns": aux["n_cols"],
        "ssm_col_iterations": aux["n_scans"],
        "overflow_retries": aux["overflow_retries"],
    }
    return result


def _columns_pass(
    packed, config, parents, creator, t_rank, coin, stake, member_table,
    *, n, tot, block, r_rounds, s_max, chain, matmul_dtype_name,
    r_cap=None, ssm_block_fn=None,
):
    """Column-restricted strongly-sees execution core.

    Strongly-see columns are pure DAG functions (round-independent), and
    the rounds scan only queries *witness* columns, so instead of the full
    Θ(N³) matrix we compute columns only as witnesses are discovered: the
    scan runs in chunks carrying its state; when a chunk registers a
    witness that has no column yet, the column is computed and just that
    chunk re-runs (exact, because columns don't depend on rounds).  Every
    query in the final pass over each chunk was answered exactly, so the
    result is bit-identical to the full-matrix scan at Θ(N²·W) cost
    (W ≈ 10% of N in gossip DAGs).  Columns are additionally computed
    only over their *suffix rows* (a row below a witness can never
    strongly-see it, and the untouched slab region is already zero — the
    exact value), which cuts the column work by the witness's depth.

    Returns ``(out, aux)``: ``out`` the numpy consensus outputs (for
    :func:`finalize_order`) and ``aux`` the live device intermediates
    (visibility slabs and the column store) that
    :class:`IncrementalConsensus` lifts into its carried state on a cold
    start or rebase.  On a fork-free history ``aux["sees"]`` *is*
    ``aux["anc"]`` (alias — see :func:`ancestry_stage`).  ``ssm_block_fn``
    overrides the strongly-sees block kernel (signature of
    :func:`ssm_block_stage`) — the mesh and Pallas backends plug in here.
    """
    n_pad = parents.shape[0]
    has_forks = bool(len(packed.fork_pairs))
    use_gather_cache = ssm_block_fn is None
    if ssm_block_fn is None:
        ssm_block_fn = functools.partial(
            obs.stage_call, "pipeline.ssm_block_stage", ssm_block_stage
        )
    o = obs.current()
    parents_d = jnp.asarray(parents)
    creator_d = jnp.asarray(creator)
    stake_d = jnp.asarray(stake)
    mt_d = jnp.asarray(member_table)
    n_d = jnp.asarray(n, dtype=jnp.int32)
    if has_forks:
        anc, sees = obs.stage_call(
            "pipeline.visibility_stage",
            visibility_stage,
            parents_d, creator_d, jnp.asarray(packed.fork_pairs),
            n_members=int(stake.shape[0]), block=block,
            matmul_dtype_name=matmul_dtype_name,
        )
    else:
        anc = obs.stage_call(
            "pipeline.visibility_stage", ancestry_stage,
            parents_d, block=block, matmul_dtype_name=matmul_dtype_name,
        )
        sees = anc          # alias: no fork pair packed -> sees == anc

    # the sees slab is frozen for the rest of the pass, so gather the
    # a-side member rows ONCE and serve every witness-column add from it
    # (same one-time cost profile as the old precomputed member slabs,
    # but transient — freed with the pass).  A custom ssm_block_fn
    # (mesh / Pallas backend) keeps the per-call path: the cache is an
    # XLA-host optimization, not part of the kernel seam.
    a_r3_full = None
    if use_gather_cache:
        a_r3_full = obs.stage_call(
            "pipeline.ssm_gather_rows", ssm_gather_rows_stage,
            sees, mt_d, np.int32(0), rows=n_pad,
        )

    # incremental column store: a preallocated (N, W_CAP) buffer written
    # in place so the scan's input shape stays stable (W_CAP grows in
    # 1024-buckets only); positions tracked host-side.  Every column is
    # exact regardless of round state.
    col_pos = np.full((n_pad,), -1, dtype=np.int32)
    n_cols = 0
    w_cap = min(_bucket(max(s_max * 8, 256), 256), n_pad)
    ssm_c = jnp.zeros((n_pad, w_cap), dtype=bool)
    n_scans = 0

    def add_columns(events):
        nonlocal n_cols, ssm_c, w_cap
        # bucket only the matmul batch and the buffer CAPACITY; occupancy
        # advances by the real count so padding slots are reused.  The
        # grain is deliberately coarse: every distinct padded width is a
        # fresh jit signature for the block kernel and the donated update,
        # and compile time — not matmul width — dominates the column path
        batch = _bucket(len(events), 64)
        if n_cols + batch > w_cap:
            w_cap = _bucket(
                max(n_cols + batch, min(w_cap * 2, n_pad)), 256
            )
            ssm_c = jnp.pad(ssm_c, ((0, 0), (0, w_cap - ssm_c.shape[1])))
        cols_arr = np.full((batch,), -1, dtype=np.int32)
        cols_arr[: len(events)] = events
        row0, rows_eff = _suffix_rows(n_pad, min(events), n_pad)
        if a_r3_full is not None:
            part = obs.stage_call(
                "pipeline.ssm_block_from_rows", ssm_block_from_rows_stage,
                a_r3_full, sees, mt_d, stake_d, jnp.asarray(cols_arr),
                np.int32(row0), rows=rows_eff, tot_stake=tot,
                matmul_dtype_name=matmul_dtype_name,
            )
        else:
            part = ssm_block_fn(
                sees, mt_d, stake_d, jnp.asarray(cols_arr), np.int32(row0),
                rows=rows_eff, tot_stake=tot,
                matmul_dtype_name=matmul_dtype_name,
            )
        for j, e in enumerate(events):
            col_pos[e] = n_cols + j
        ssm_c = update_block_stage(
            ssm_c, part, np.int32(row0), np.int32(n_cols)
        )
        n_cols += len(events)

    add_columns([int(i) for i in np.where(packed.parents[:, 0] < 0)[0]])

    # chunked scan: resume from the carried state; when a chunk registers
    # a witness whose column is missing AND a later event in the chunk
    # queried that witness's round, compute the column and re-run just
    # that chunk (columns are round-independent, so the re-run is exact);
    # otherwise the chunk's outputs are already exact and the new columns
    # only serve future chunks.  Witness-table overflow self-heals: the
    # scan restarts with the flagged capacity grown (the column store
    # survives retries — columns never depend on the table shape), so an
    # under-provisioned r_max/s_max degrades to a slower pass, never a
    # crash.
    chunk_size = min(128, n_pad)
    while n_pad % chunk_size:
        chunk_size //= 2
    parents_np = parents
    if r_cap is None:
        r_cap = max(int(config.max_rounds), r_rounds)
    overflow_retries = 0
    while True:
        state = (
            jnp.zeros((n_pad,), dtype=jnp.int32),
            jnp.zeros((n_pad,), dtype=bool),
            jnp.full((r_rounds, s_max), -1, dtype=jnp.int32),
            jnp.zeros((r_rounds,), dtype=jnp.int32),
            jnp.zeros((), dtype=jnp.int32),
        )
        for start in range(0, n_pad, chunk_size):
            start_d = jnp.asarray(start, dtype=jnp.int32)
            # each failed attempt adds at least one column, and a chunk can
            # register at most chunk_size witnesses, so this bound is safe
            # even for degenerate one-round-per-event DAGs (2-member gossip)
            for _attempt in range(chunk_size + 1):
                out = obs.stage_call(
                    "pipeline.rounds_chunk_stage",
                    rounds_chunk_stage,
                    parents_d, ssm_c, jnp.asarray(col_pos), creator_d,
                    stake_d, n_d, *state, start_d,
                    jnp.zeros((), dtype=jnp.int32),
                    tot_stake=tot, r_max=r_rounds, s_max=s_max,
                    has_forks=has_forks, chunk=chunk_size,
                )
                n_scans += 1
                tab = np.asarray(out[2])
                registered = np.unique(tab[tab >= 0])
                missing = registered[col_pos[registered] < 0]
                if missing.size == 0:
                    state = out
                    break
                rnd_np = np.asarray(out[0])
                # was a missing witness's round queried later in this chunk?
                ce = np.arange(start, start + chunk_size, dtype=np.int64)
                p = parents_np[ce]
                r0 = np.where(
                    p[:, 0] < 0,
                    -1,
                    np.maximum(rnd_np[np.maximum(p[:, 0], 0)],
                               rnd_np[np.maximum(p[:, 1], 0)]),
                )
                affected = False
                for w in missing:
                    if w < start:   # registered in an earlier chunk state?
                        affected = True  # (shouldn't happen; be safe)
                        break
                    later = ce > w
                    if np.any(later & (r0 == rnd_np[w])):
                        affected = True
                        break
                add_columns([int(e) for e in missing])
                if not affected:
                    state = out
                    break
            else:
                raise RuntimeError("witness-column chunk did not converge")
            if int(np.asarray(state[4])):
                break               # overflow: stop scanning, grow, retry
        ovf = int(np.asarray(state[4]))
        if not ovf:
            break
        r_rounds, s_max = _healed_capacities(
            ovf, r_eff=r_rounds, r_cap=r_cap, s_eff=s_max, s_cap=n_pad,
        )
        overflow_retries += 1
    rnd_a, wits_a, tab_a, cnt_a, _overflow_a = state
    max_round_d = jnp.max(jnp.where(jnp.arange(n_pad) < n_d, rnd_a, 0))
    max_round = int(max_round_d)
    r_tight = min(r_rounds, _bucket(max_round + 3, 8))
    stage_b = obs.stage_call(
        "pipeline.fame_order_cols_stage",
        fame_order_cols_stage,
        anc, sees, ssm_c, jnp.asarray(col_pos), tab_a, cnt_a,
        creator_d, jnp.asarray(coin), stake_d,
        jnp.asarray(parents[:, 0]), jnp.asarray(t_rank),
        max_round_d, n_d,
        tot_stake=tot, coin_period=config.coin_period, r_max=r_tight,
        s_max=s_max, chain=chain, has_forks=has_forks,
        matmul_dtype_name=matmul_dtype_name,
    )
    out = {
        "round": rnd_a,
        "is_witness": wits_a,
        "wit_table": tab_a[:r_tight],
        "wit_count": cnt_a[:r_tight],
        "max_round": max_round_d,
        **stage_b,
    }
    out = jax.tree.map(np.asarray, out)
    aux = {
        "anc": anc, "sees": sees, "ssm_c": ssm_c,
        "col_pos": col_pos, "n_cols": n_cols, "w_cap": w_cap,
        "n_scans": n_scans, "r_rounds": r_rounds, "s_max": s_max,
        "overflow_retries": overflow_retries,
    }
    return out, aux


def _unique_famous(fam_events, creators) -> List[int]:
    """Unique famous witnesses of one round: famous witnesses whose
    creator has exactly one famous witness there — the shared commit rule
    of :func:`finalize_order` and the incremental driver (keep the two in
    lock-step: any change here is a consensus-rule change)."""
    by_creator: Dict[int, List[int]] = {}
    for e in fam_events:
        by_creator.setdefault(int(creators[e]), []).append(e)
    return sorted(e for v in by_creator.values() if len(v) == 1 for e in v)


def _whiten_sigs(sigs) -> bytes:
    """XOR-fold the UFW signatures into the round's tiebreak whitener."""
    w = bytes(crypto.SIG_BYTES)
    for s in sigs:
        w = xor_bytes(w, s)
    return w


def finalize_order(
    packed: PackedDAG, out: Dict[str, np.ndarray], ts_unique: np.ndarray
) -> ConsensusResult:
    """Host post-pass: fame dict, whitened tiebreak, final total order."""
    n = packed.n
    tab = out["wit_table"]
    famous_grid = out["famous"].reshape(tab.shape)
    famous: Dict[int, Optional[bool]] = {}
    r_max, s_max = tab.shape
    ufw_by_round: Dict[int, List[int]] = {}
    for r in range(r_max):
        fam_slots = []
        for s in range(s_max):
            e = int(tab[r, s])
            if e < 0:
                continue
            f = int(famous_grid[r, s])
            famous[e] = None if f < 0 else bool(f)
            if f == 1:
                fam_slots.append(e)
        if fam_slots:
            ufw_by_round[r] = _unique_famous(fam_slots, packed.creator)

    rr = out["round_received"][:n]
    # map timestamp ranks back to the int64 values
    rank = np.clip(out["consensus_ts_rank"][:n], 0, len(ts_unique) - 1)
    cts = np.where(rr >= 0, ts_unique[rank], 0).astype(np.int64)
    whiten_cache: Dict[int, bytes] = {}

    def whiten(r: int) -> bytes:
        w = whiten_cache.get(r)
        if w is None:
            w = _whiten_sigs(packed.sigs[e] for e in ufw_by_round.get(r, []))
            whiten_cache[r] = w
        return w

    received = [
        (int(rr[i]), int(cts[i]), crypto.hash_bytes(whiten(int(rr[i])) + packed.ids[i]), i)
        for i in range(n)
        if rr[i] >= 0
    ]
    received.sort(key=lambda item: (item[0], item[1], item[2]))
    return ConsensusResult(
        n=n,
        round=out["round"][:n],
        is_witness=out["is_witness"][:n],
        famous=famous,
        round_received=rr,
        consensus_ts=cts,
        order=[i for (_r, _t, _h, i) in received],
        max_round=int(out["max_round"]),
    )


# ------------------------------------------- incremental (windowed) stages
#
# Steady-state consensus never re-decides the committed prefix: the driver
# below (:class:`IncrementalConsensus`) carries the visibility slabs, the
# strongly-sees column store, and the per-round decisions on device between
# passes, extends them with only the new-event rows/columns, and prunes the
# decided prefix so every matrix dimension scales with the *undecided
# window* rather than total history.  All stages take the carried slab as a
# donated argument so XLA updates it in place where the backend supports
# donation, and every shape is a session-monotone bucket so the steady
# loop hits a warm jit cache (no per-pass recompiles).
#
# The extension hot path is **pluggable** (:class:`ExtensionKernels`): the
# blockwise boolean-matmul hop of the ancestry extension and the
# strongly-sees block kernel can be swapped for Pallas tile kernels
# (:func:`tpu_swirld.tpu.pallas_kernels.make_extension_kernels`) or the
# mesh-sharded variant (:func:`tpu_swirld.parallel.make_ssm_block_fn_for_
# mesh`); the default XLA implementations and the interpret-mode Pallas
# kernels are bit-identical (0/1 products, f32 accumulation, integer
# thresholds), pinned by ``tests/test_pallas.py``.


@dataclasses.dataclass(frozen=True)
class ExtensionKernels:
    """Kernel bundle for the window-extension hot path.

    ``name`` keys the fused-stage jit cache; ``bmm`` is the boolean-matmul
    hop ``(a, b, dtype) -> bool`` used by the blockwise ancestry
    extension (None = the XLA :func:`_bmm`); ``ssm_block_fn`` matches
    :func:`ssm_block_stage` (None = that stage).
    """

    name: str
    bmm: Optional[object] = None
    ssm_block_fn: Optional[object] = None


XLA_EXTENSION_KERNELS = ExtensionKernels(name="xla")

_extend_vis_stages: Dict = {}


def _ancestry_extend_body(anc, parents, b0, b1, *, block, dt, bmm):
    """Extend the carried ancestry slab with rows for blocks [b0, b1).

    Identical math to :func:`ancestry` resumed over an existing slab:
    rows below ``b0 * block`` are read, not recomputed, so the work is
    O(new rows x window).  A partially filled boundary block is recomputed
    idempotently (same parent rows -> same values).  Parents of pruned
    events are remapped to -1 by the driver; that is exact here because a
    pruned parent's ancestry over the retained columns is all-zero (topo
    order: nothing retained is older than a pruned event).
    """
    n = parents.shape[0]
    n_sq = max(1, math.ceil(math.log2(block)))
    eye = jnp.eye(block, dtype=bool)
    jj = jnp.arange(block)

    def body(k, r):
        s = k * block
        pb = lax.dynamic_slice(parents, (s, 0), (block, 2))
        local = pb - s
        adj = (local[:, 0:1] == jj[None, :]) | (local[:, 1:2] == jj[None, :])
        lc = adj | eye
        for _ in range(n_sq):
            lc = lc | bmm(lc, lc, dt)
        pc = jnp.clip(pb, 0, n - 1)
        ext = (pb >= 0) & (pb < s)
        g = (r[pc[:, 0]] & ext[:, 0:1]) | (r[pc[:, 1]] & ext[:, 1:2])
        rows = bmm(lc, g, dt)
        diag = lax.dynamic_slice(rows, (0, s), (block, block)) | lc
        rows = lax.dynamic_update_slice(rows, diag, (0, s))
        return lax.dynamic_update_slice(r, rows, (s, 0))

    return lax.fori_loop(b0, b1, body, anc)


def make_extend_visibility_stage(kern: ExtensionKernels):
    """Fork-free fused extension: ancestry blocks only (``sees`` aliases
    ``anc``).  One donated jit dispatch per ingest pass."""
    fn = _extend_vis_stages.get((kern.name, "noforks"))
    if fn is None:
        bmm = kern.bmm or _bmm

        @functools.partial(
            jax.jit,
            static_argnames=("block", "matmul_dtype_name"),
            donate_argnums=(0,),
        )
        def extend_visibility_stage(anc, parents, b0, b1, *, block,
                                    matmul_dtype_name):
            dt = (
                jnp.bfloat16 if matmul_dtype_name == "bfloat16"
                else jnp.float32
            )
            return _ancestry_extend_body(
                anc, parents, b0, b1, block=block, dt=dt, bmm=bmm
            )

        fn = extend_visibility_stage
        _extend_vis_stages[(kern.name, "noforks")] = fn
    return fn


def make_extend_visibility_forked_stage(kern: ExtensionKernels):
    """Forked fused extension: ancestry blocks plus fork-aware sees rows
    ``[row0, row0 + rows)`` in one donated jit dispatch.

    Only new sees rows are written: an already-present event never changes
    its visibility (a fork pair only exists once its second member is
    packed, and nothing older descends from it), and old rows over new
    columns are structurally zero (topo order), so extension is exact.
    ``fork_pairs`` are window-remapped; the driver rebases whenever a pair
    member falls below the pruned boundary, so every pair is addressable.
    """
    fn = _extend_vis_stages.get((kern.name, "forked"))
    if fn is None:
        bmm = kern.bmm or _bmm

        @functools.partial(
            jax.jit,
            static_argnames=(
                "block", "rows", "n_members", "matmul_dtype_name"
            ),
            donate_argnums=(0, 1),
        )
        def extend_visibility_forked_stage(
            anc, sees, parents, fork_pairs, creator, b0, b1, row0, *,
            block, rows, n_members, matmul_dtype_name,
        ):
            dt = (
                jnp.bfloat16 if matmul_dtype_name == "bfloat16"
                else jnp.float32
            )
            anc = _ancestry_extend_body(
                anc, parents, b0, b1, block=block, dt=dt, bmm=bmm
            )
            n = anc.shape[0]
            anc_rows = lax.dynamic_slice(anc, (row0, 0), (rows, n))
            mcol = fork_pairs[:, 0]
            a = jnp.clip(fork_pairs[:, 1], 0, n - 1)
            b = jnp.clip(fork_pairs[:, 2], 0, n - 1)
            hit = anc_rows[:, a] & anc_rows[:, b] & (mcol >= 0)[None, :]
            onehot = mcol[:, None] == jnp.arange(n_members)[None, :]
            fseen = bmm(hit, onehot, dt)
            new_rows = anc_rows & ~fseen[:, creator]
            sees = lax.dynamic_update_slice(sees, new_rows, (row0, 0))
            return anc, sees

        fn = extend_visibility_forked_stage
        _extend_vis_stages[(kern.name, "forked")] = fn
    return fn


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def prune_stage(anc, sees, ssm_c, d, n_used, keep_cols):
    """Shift the carried slabs down/left by ``d`` pruned events, zero the
    vacated tail, and gather the surviving witness columns (``keep_cols``
    indexes the old column slots, -1 = vacate).  Capacities are preserved
    so the steady loop keeps a single compiled shape."""
    n = anc.shape[0]
    live = jnp.arange(n) < (n_used - d)
    m2 = live[:, None] & live[None, :]
    anc = jnp.roll(jnp.roll(anc, -d, axis=0), -d, axis=1) & m2
    sees = jnp.roll(jnp.roll(sees, -d, axis=0), -d, axis=1) & m2
    kv = keep_cols >= 0
    kc = jnp.clip(keep_cols, 0, ssm_c.shape[1] - 1)
    ssm_c = jnp.roll(ssm_c, -d, axis=0)[:, kc] & live[:, None] & kv[None, :]
    return anc, sees, ssm_c


@functools.partial(jax.jit, donate_argnums=(0, 1))
def prune_noforks_stage(anc, ssm_c, d, n_used, keep_cols):
    """:func:`prune_stage` for the fork-free fast path: the sees slab is
    an alias of ``anc``, so only two slabs roll."""
    n = anc.shape[0]
    live = jnp.arange(n) < (n_used - d)
    m2 = live[:, None] & live[None, :]
    anc = jnp.roll(jnp.roll(anc, -d, axis=0), -d, axis=1) & m2
    kv = keep_cols >= 0
    kc = jnp.clip(keep_cols, 0, ssm_c.shape[1] - 1)
    ssm_c = jnp.roll(ssm_c, -d, axis=0)[:, kc] & live[:, None] & kv[None, :]
    return anc, ssm_c


@jax.jit
def _copy_slab_stage(anc):
    """Materialize a distinct sees slab from the ancestry slab (the
    fork-free alias ends when the first fork pair arrives)."""
    return anc | False      # an actual op: forces a fresh buffer


@functools.partial(jax.jit, donate_argnums=(0,))
def compact_cols_stage(ssm_c, keep_cols):
    """Gather the surviving witness columns without a row shift — the
    roll-time compaction that keeps retired-round columns from padding
    every ssm block matmul until the next prune."""
    kv = keep_cols >= 0
    kc = jnp.clip(keep_cols, 0, ssm_c.shape[1] - 1)
    return ssm_c[:, kc] & kv[None, :]


@functools.partial(
    jax.jit,
    static_argnames=(
        "tot_stake", "coin_period", "r_max", "s_max", "has_forks",
        "matmul_dtype_name",
    ),
)
def fame_window_stage(sees, ssm_c, col_pos, wit_table, creator, coin, stake,
                      *, tot_stake, coin_period, r_max, s_max, has_forks,
                      matmul_dtype_name):
    """Fame voting over the retained round window only.  Round-window
    locality is exact: votes about a round-r witness only involve rounds
    > r, and the driver's straggler guard rebases whenever a witness
    registers below the window, so rows [0, r_max) are self-contained."""
    dt = jnp.bfloat16 if matmul_dtype_name == "bfloat16" else jnp.float32
    return fame_scan(
        wit_table[:r_max], sees, ssm_c, creator, coin, stake, tot_stake,
        coin_period, dt, has_forks=has_forks, col_pos=col_pos,
    )


@functools.partial(jax.jit, static_argnames=("r_max", "s_max", "chain"))
def order_window_stage(anc, wit_table, wit_count, famous, creator,
                       self_parent, t_rank, max_round_local, n_valid,
                       received0, *, r_max, s_max, chain):
    """Order extraction over the first ``r_max`` retained rounds, resuming
    from the carried received flags.  Already-committed rounds re-run as
    no-ops (their received sets are final — new events are never ancestors
    of old witnesses), so ``r_max`` only needs to reach the newly
    fame-complete prefix."""
    return order_scan(
        anc, wit_table[:r_max], wit_count[:r_max],
        famous[: r_max * s_max], creator, self_parent, t_rank,
        max_round_local, n_valid, chain=chain, received0=received0,
    )


# --------------------------------------------------- incremental driver


class IncrementalConsensus:
    """Steady-state consensus driver with carried device state.

    Where :func:`run_consensus` recomputes the full ancestry / sees /
    strongly-sees matrices on every call, this driver keeps them (plus the
    witness table and per-round decisions) alive between passes:

    - :meth:`ingest` appends a gossip delta to the internal
      :class:`~tpu_swirld.packing.Packer`, extends the carried slabs with
      only the new-event rows/columns, resumes the rounds scan from its
      carried state, re-votes fame over the *retained round window* only,
      and extracts the order of newly fame-complete rounds;
    - the **decided prefix is pruned**: once an event is received (and all
      fork-pair members stay above the cut), its row/column is dropped
      from every slab, so matrix work scales with the undecided window
      rather than total history;
    - all static shapes are session-monotone buckets, so after a short
      warmup the steady loop adds **zero new jit-cache entries**, and the
      carried slabs are donated to the extension stages.

    Exactness contract: every pass leaves the committed outputs **bit-
    identical** to a cold :func:`run_consensus` over the full DAG.  Window
    locality is exact for gossip-shaped traffic (new events reference
    recent parents); the cases where it is not are *detected* and answered
    with a transparent full recompute ("rebase"):

    - a new event whose parent was already pruned, or whose parent round
      fell below the retained round window (deep orphan/straggler),
    - a new witness registering at a round at or below the frozen vote
      horizon (it could change a committed fame tally),
    - a new fork pair naming a pruned event,
    - witness-table overflow (round/slot capacity).

    Rebases rebuild the carried state from the batch pipeline, so they
    cost one cold pass and the driver keeps going.
    """

    def __init__(
        self,
        members,
        stake=None,
        config: Optional[SwirldConfig] = None,
        *,
        block: int = 128,
        chunk: int = 256,
        window_bucket: int = 1024,
        prune_min: Optional[int] = None,
        matmul_dtype_name: Optional[str] = None,
        ssm_block_fn=None,
        extension_kernels: Optional[ExtensionKernels] = None,
        storm_threshold: int = 3,
        storm_cooldown: int = 8,
        slab_put=None,
        fuse_chunks: Optional[int] = None,
    ):
        if stake is None:
            stake = [1] * len(members)
        self.packer = Packer(members, stake)
        self.config = config or SwirldConfig(n_members=len(members))
        self._block = block
        self._chunk = max(32, chunk)
        # dispatch fusion: how many rounds-scan chunks one device
        # dispatch covers (rounds_span_stage).  <= 1 keeps the original
        # per-chunk loop; resolution order is explicit kwarg > config
        # field > SWIRLD_FUSE_CHUNKS env > default (see config module)
        if fuse_chunks is None:
            fuse_chunks = resolve_stream_settings(self.config)["fuse_chunks"]
        self._fuse = max(1, int(fuse_chunks))
        self._window_bucket = max(256, window_bucket)
        self._prune_min = (
            prune_min if prune_min is not None else self._window_bucket // 4
        )
        if matmul_dtype_name is None:
            matmul_dtype_name = (
                "float32" if jax.default_backend() == "cpu" else "bfloat16"
            )
        self._mm = matmul_dtype_name
        self._kern = (
            extension_kernels if extension_kernels is not None
            else XLA_EXTENSION_KERNELS
        )
        # the per-pass a-side gather cache only matches the default XLA
        # block kernel; a custom seam (mesh / Pallas) owns its own gathers
        self._cache_blocks = (
            ssm_block_fn is None and self._kern.ssm_block_fn is None
        )
        self._ars_cache = None      # (row0, rows) -> pre-gathered a-side
        self._ars_key = None
        if ssm_block_fn is None:
            base = self._kern.ssm_block_fn or ssm_block_stage
            ssm_block_fn = functools.partial(
                obs.stage_call, "pipeline.ssm_block_stage", base
            )
        self._ssm_block_fn = ssm_block_fn
        # slab placement seam: every from-scratch slab push (rebase,
        # widening) goes through this, so a mesh driver can scatter the
        # window rows to their owning devices instead of replicating
        self._put = slab_put if slab_put is not None else jnp.asarray
        self._stake = np.asarray(stake, dtype=np.int32)
        self._tot = int(self._stake.sum())
        self._m = len(members)

        # global committed outputs (amortized-growth buffers)
        self._round_g = np.zeros((0,), np.int32)
        self._wits_g = np.zeros((0,), bool)
        self._rr_g = np.zeros((0,), np.int32)
        self._cts_g = np.zeros((0,), np.int64)
        self._order: List[int] = []
        self._famous_committed: Dict[int, bool] = {}

        # consensus cursors (global rounds / indices)
        self._initialized = False
        self._n_done = 0            # events consumed from the packer
        self._lo = 0                # pruned prefix length (global index)
        self._r_base = 0            # global round of witness-table row 0
        self._consensus_round = 0   # next round to order (== r_base at rest)
        self._frozen_vote_hi = 0    # votes at rounds < this are committed
        self._max_round = 0
        self._g_done = 0            # fork pairs already vetted

        # session-monotone static shape buckets (recompile hygiene)
        self._w_pad = 0             # window row capacity
        self._rows_hi = 0           # high-water of materialized window rows
        self._wcol_cap = 256        # ssm column capacity
        self._r_cap = 32            # witness-table rows
        self._r_fame = 8            # fame round window
        self._r_ord = 4             # order round window
        self._chain_cap = 32        # self-chain walk depth
        self._k_cap = 8             # member-table columns
        self._g_cap = 0             # fork-pair rows
        self._s_cap = self._m + 1   # witness slots per round

        # telemetry
        self.passes = 0
        self.rebases = 0
        self.recompiles_hint = 0
        self.overflow_heals = 0   # capacity growths absorbed by rebases
        self.finality = None      # obs.FinalityTracker: per-event
                                  # lifecycle (births at ingest, decided
                                  # at commit — see _stats)
        self.flightrec = None     # obs.FlightRecorder: storm/overflow
                                  # anomalies dump post-mortems
        self.flightrec_label = "incremental"
        # latency-phase attribution: the streaming driver stamps each
        # pass's decided events with "window" / "widened" / "full"
        # (window residency vs archive widening); plain incremental
        # leaves both None (no phase dimension)
        self._latency_phase = None
        self._latency_phase_default = None

        # rebase-storm guard: adversarial ingest (straggler floods, deep
        # orphan replays) can make EVERY pass detect-then-rebase, paying
        # the doomed incremental attempt on top of the full recompute.
        # After `storm_threshold` consecutive detected rebases the driver
        # flips to full-recompute mode for `storm_cooldown` passes
        # (skipping the extension attempt entirely), then re-admits the
        # incremental path with a fresh slate — a hysteresis loop, so
        # thrash can't oscillate pass-by-pass.  storm_threshold <= 0
        # disables the guard (the thrash-measuring control in tests).
        self.storm_threshold = storm_threshold
        self.storm_cooldown = max(1, storm_cooldown)
        self.storm_entries = 0            # times the guard engaged
        self.storm_rebases = 0            # rebases run in storm mode
        self.max_consecutive_rebases = 0  # worst detect-rebase streak
        self._consec_rebases = 0
        self._storm_left = 0

    # ------------------------------------------- capacity growth policy
    #
    # Single source of truth for the next-capacity formulas: the
    # streaming driver's budget pre-checks predict the exact shapes these
    # produce, so any policy change here must stay in one place.

    @staticmethod
    def _next_row_pad(need: int, window_bucket: int) -> int:
        return _bucket(need + window_bucket // 2, window_bucket)

    @staticmethod
    def _next_col_cap(n_cols: int, batch: int, cap: int) -> int:
        return _bucket(max(n_cols + batch, cap * 2), 256)

    @staticmethod
    def _next_k_cap(need: int) -> int:
        # K is a dimension of every gather/block kernel signature, so it
        # must step rarely: 25% headroom on a coarse grain keeps the
        # session to a handful of K values instead of one every 8 events
        # per member (padding is -1 -> masked, exact)
        return _bucket(need + need // 4 + 8, 32)

    # -------------------------------------------------------- public API

    def __len__(self) -> int:
        return self._n_done

    @property
    def window_size(self) -> int:
        return self._n_done - self._lo

    @property
    def pruned_prefix(self) -> int:
        return self._lo

    @property
    def storm_mode(self) -> bool:
        """True while the rebase-storm guard holds the driver in
        full-recompute mode."""
        return self._storm_left > 0

    @property
    def resident_visibility_bytes(self) -> int:
        """Bytes of device-resident visibility state (the anc/sees/ssm
        window slabs; sees aliases anc on a fork-free history and the old
        per-member gather slabs no longer exist) — the quantity the slab
        store's tile budget bounds.  Zero before the first pass."""
        if not self._initialized:
            return 0
        n = int(self._anc_d.nbytes + self._ssm_d.nbytes)
        if self._sees_d is not self._anc_d:
            n += int(self._sees_d.nbytes)
        return n

    # Retirement hooks: no-ops here; :class:`tpu_swirld.store.streaming.
    # StreamingConsensus` overrides them to archive decided rows / rounds
    # instead of discarding them.  Called with the PRE-mutation state.

    def _on_prune(self, d: int, w_used: int) -> None:
        """About to drop window rows [0, d) of [0, w_used)."""

    def _on_roll(self, dr: int) -> None:
        """About to roll witness-table rows [0, dr) out of the window."""

    def _on_rebase(self, packed, out, aux) -> None:
        """A batch rebase decided everything up to the new ``self._lo``;
        ``aux`` still holds the full-DAG device slabs."""

    def _pack_delta(self, events) -> None:
        """Append a gossip delta to the packer.  Seam for the streaming
        driver's decode-overlap path, which substitutes pre-decoded
        ``(event, id)`` pairs produced on a worker thread — the override
        must keep all packer mutation on the calling thread."""
        self.packer.extend(events)

    def ingest(self, events=()) -> Dict:
        """Feed a topo-ordered gossip delta; run one incremental pass.

        Returns a per-pass stats dict: ``new_events``, ``ordered`` (the
        packed indices newly committed to the total order, in order),
        ``window_size``, ``pruned_prefix``, ``rebased``, ``seconds``.
        """
        t0 = time.perf_counter()
        _o = obs.current()
        if _o is not None and _o.profiler is not None:
            # one profiler chunk per pass: _stats() closes it, so every
            # return path yields a dispatch-overhead breakdown row
            _o.profiler.begin_chunk()
        n_before = len(self.packer)
        self._pack_delta(events)
        n_total = len(self.packer)
        if self.finality is not None and n_total > n_before:
            # birth = the tick this ingest chunk entered the driver; the
            # tracker's clock decides the unit (logical tick vs seconds)
            self.finality.mark_births(n_before, n_total)
        n_new = n_total - self._n_done
        if n_total == 0 or (n_new == 0 and self._initialized):
            return self._stats(n_new, [], t0, rebased=False)
        if not self._initialized:
            # the cold-start build is a rebase mechanically but not a
            # *failed incremental attempt* — it never feeds the guard
            ordered = self._rebase()
            return self._stats(n_new, ordered, t0, rebased=True,
                               count_storm=False)
        if self._storm_left > 0:
            # storm mode: skip the doomed detect/extend attempt outright
            self._storm_left -= 1
            self.storm_rebases += 1
            if self._storm_left == 0:
                self._consec_rebases = 0   # hysteresis exit: fresh slate
            ordered = self._rebase()
            return self._stats(n_new, ordered, t0, rebased=True,
                               count_storm=False, storm=True)
        if self._needs_rebase_pre():
            ordered = self._rebase()
            return self._stats(n_new, ordered, t0, rebased=True)
        ordered, need_rebase = self._extend_pass(n_new)
        if need_rebase:
            ordered = self._rebase()
            return self._stats(n_new, ordered, t0, rebased=True)
        return self._stats(n_new, ordered, t0, rebased=False)

    def result(self) -> ConsensusResult:
        """Cumulative consensus state — bit-identical to a cold
        :func:`run_consensus` over the same packed DAG."""
        n = self._n_done
        famous: Dict[int, Optional[bool]] = dict(self._famous_committed)
        if self._initialized:
            for k in range(self._r_cap):
                for s in range(self._s_cap):
                    e = int(self._tab_np[k, s])
                    if e < 0:
                        continue
                    f = int(self._famous_np[k, s])
                    famous[self._lo + e] = None if f < 0 else bool(f)
        return ConsensusResult(
            n=n,
            round=self._round_g[:n].copy(),
            is_witness=self._wits_g[:n].copy(),
            famous=famous,
            round_received=self._rr_g[:n].copy(),
            consensus_ts=self._cts_g[:n].copy(),
            order=list(self._order),
            max_round=self._max_round,
            timings={
                "passes": self.passes,
                "rebases": self.rebases,
                "window_size": self.window_size,
                "pruned_prefix": self.pruned_prefix,
                "storm_entries": self.storm_entries,
                "storm_rebases": self.storm_rebases,
                "max_consecutive_rebases": self.max_consecutive_rebases,
            },
        )

    # ------------------------------------------------------ pass plumbing

    def _stats(self, n_new, ordered, t0, *, rebased,
               count_storm=True, storm=False):
        self.passes += 1
        if rebased:
            self.rebases += 1
            if count_storm:
                # a *detected* rebase: an incremental attempt that failed
                self._consec_rebases += 1
                self.max_consecutive_rebases = max(
                    self.max_consecutive_rebases, self._consec_rebases
                )
                if (
                    self.storm_threshold > 0
                    and self._consec_rebases >= self.storm_threshold
                ):
                    self.storm_entries += 1
                    self._storm_left = self.storm_cooldown
                    if self.flightrec is not None:
                        oo = obs.current()
                        self.flightrec.trigger(
                            "rebase_storm", node=self.flightrec_label,
                            detail={
                                "consecutive": self._consec_rebases,
                                "cooldown": self.storm_cooldown,
                            },
                            decided_frontier={
                                self.flightrec_label: {
                                    "decided": len(self._order),
                                    "round": self._consensus_round,
                                },
                            },
                            registry=oo.registry if oo is not None else None,
                        )
        elif n_new > 0:
            self._consec_rebases = 0   # a clean incremental pass
        # a storm-mode pass must report as such even when it was the last
        # one of the cooldown (_storm_left was decremented before _stats)
        in_storm = storm or self._storm_left > 0
        o = obs.current()
        if o is not None:
            g = o.registry
            g.gauge("incremental_window_size").set(self.window_size)
            g.gauge("incremental_pruned_prefix").set(self.pruned_prefix)
            g.gauge("incremental_r_base").set(self._r_base)
            g.gauge("incremental_storm_mode").set(1.0 if in_storm else 0.0)
            g.gauge("incremental_consecutive_rebases").set(
                self._consec_rebases
            )
            g.counter("incremental_passes_total").inc()
            if rebased:
                g.counter("incremental_rebases_total").inc()
            if storm:
                g.counter("incremental_storm_rebases_total").inc()
        fin = self.finality
        if fin is not None and ordered:
            phase = self._latency_phase
            now = fin.now()
            for gi in ordered:
                gi = int(gi)
                fin.record_decided(
                    gi, int(self._round_g[gi]), int(self._rr_g[gi]),
                    now=now, phase=phase,
                )
            fin.set_watermark(
                self.flightrec_label, len(self._order),
                self._consensus_round - 1,
            )
        self._latency_phase = self._latency_phase_default
        if o is not None and o.profiler is not None:
            o.profiler.end_chunk(n_events=int(n_new))
        return {
            "new_events": int(n_new),
            "ordered": ordered,
            "window_size": self.window_size,
            "pruned_prefix": self.pruned_prefix,
            "rebased": bool(rebased),
            "storm_mode": in_storm,
            "seconds": round(time.perf_counter() - t0, 6),
        }

    def _grow_global(self, n: int) -> None:
        if self._round_g.shape[0] >= n:
            return
        cap = max(n, 2 * max(1, self._round_g.shape[0]))

        def regrow(a, fill, dtype):
            out = np.full((cap,), fill, dtype)
            out[: a.shape[0]] = a
            return out

        self._round_g = regrow(self._round_g, 0, np.int32)
        self._wits_g = regrow(self._wits_g, False, bool)
        self._rr_g = regrow(self._rr_g, -1, np.int32)
        self._cts_g = regrow(self._cts_g, 0, np.int64)

    def _needs_rebase_pre(self) -> bool:
        """Host-side guards that must run before touching device state."""
        p = self.packer
        lo, n0, n1 = self._lo, self._n_done, len(p)
        new_par, _, _, _ = p.window_view(n0, n1)
        live = new_par >= 0
        if live.any() and int(new_par[live].min()) < lo:
            return True          # parent already pruned
        if self._r_base > 0 and (~live[:, 0]).any():
            return True          # late genesis: a round-0 straggler
        # Parent rounds must stay inside the retained round window.  Only
        # events whose parents are BOTH already processed can be checked
        # against the round mirror; events referencing a parent inside
        # this same delta are covered by induction (round >= parent round,
        # and every chain bottoms out in a checked old parent).
        both_old = live[:, 0] & (new_par < n0).all(axis=1)
        if both_old.any():
            pw = np.where(both_old[:, None], new_par - lo, 0)
            r0 = self._rnd_w[pw].max(axis=1)
            if int(r0[both_old].min()) < self._r_base:
                return True
        # new fork pairs must not name pruned events
        if p.n_fork_pairs > self._g_done:
            pairs = p.fork_pairs_view(self._g_done)
            if int(pairs[:, 1:].min()) < lo:
                return True
        return False

    # --------------------------------------------------- capacity buckets

    def _ensure_row_capacity(self, need: int) -> None:
        if need <= self._w_pad:
            return
        new_pad = self._next_row_pad(need, self._window_bucket)
        g = new_pad - self._w_pad
        self._ars_cache = self._ars_key = None
        aliased = self._sees_d is self._anc_d
        self._anc_d = jnp.pad(self._anc_d, ((0, g), (0, g)))
        self._sees_d = (
            self._anc_d if aliased
            else jnp.pad(self._sees_d, ((0, g), (0, g)))
        )
        self._ssm_d = jnp.pad(self._ssm_d, ((0, g), (0, 0)))
        self._grow_mirrors(new_pad)
        self._w_pad = new_pad

    def _grow_mirrors(self, new_pad: int) -> None:
        def regrow(a, fill):
            out = np.full((new_pad,) + a.shape[1:], fill, a.dtype)
            out[: a.shape[0]] = a
            return out

        self._parents_w = regrow(self._parents_w, -1)
        self._creator_w = regrow(self._creator_w, 0)
        self._coin_w = regrow(self._coin_w, 0)
        self._t_w = regrow(self._t_w, 0)
        self._rnd_w = regrow(self._rnd_w, 0)
        self._wits_w = regrow(self._wits_w, False)
        self._recv_w = regrow(self._recv_w, False)
        self._depth_w = regrow(self._depth_w, 0)
        self._colpos_w = regrow(self._colpos_w, -1)

    def _alloc_mirrors(self, w_pad: int) -> None:
        self._parents_w = np.full((w_pad, 2), -1, np.int32)
        self._creator_w = np.zeros((w_pad,), np.int32)
        self._coin_w = np.zeros((w_pad,), np.uint8)
        self._t_w = np.zeros((w_pad,), np.int64)
        self._rnd_w = np.zeros((w_pad,), np.int32)
        self._wits_w = np.zeros((w_pad,), bool)
        self._recv_w = np.zeros((w_pad,), bool)
        self._depth_w = np.zeros((w_pad,), np.int32)
        self._colpos_w = np.full((w_pad,), -1, np.int32)

    def _grow_k(self, need: int) -> None:
        new_k = self._next_k_cap(need)
        out = np.full((self._m, new_k), -1, np.int32)
        out[:, : self._k_cap] = self._mt_np
        self._mt_np = out
        self._k_cap = new_k

    def _rebuild_member_table(self, w_used: int) -> None:
        """Vectorized member-table rebuild over window rows [0, w_used):
        per member, its window events in window (topo) order — identical
        to the old sequential registration loop, O(w log w) numpy."""
        cre = self._creator_w[:w_used].astype(np.int64)
        counts = np.bincount(cre, minlength=self._m)
        kmax = int(counts.max(initial=0))
        if kmax > self._k_cap:
            self._k_cap = self._next_k_cap(kmax)
        self._mt_np = np.full((self._m, self._k_cap), -1, np.int32)
        self._mcount = counts.astype(np.int32)
        if w_used:
            order = np.argsort(cre, kind="stable")
            starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
            kpos = np.arange(w_used, dtype=np.int64) - np.repeat(starts, counts)
            self._mt_np[cre[order], kpos] = order.astype(np.int32)

    def _materialize_sees(self) -> None:
        """Fork-free -> forked transition: give sees its own slab.

        Exact without recomputation: the first fork pair's second member
        is in the *pending* delta (the packer creates a pair when the
        second member arrives), so no already-present row descends from
        the pair — every existing row's fseen is all-zero and its sees
        row equals its ancestry row.  The extension pass then writes the
        new (possibly poisoned) rows on top of the copy."""
        if self._initialized and self._sees_d is self._anc_d:
            self._ars_cache = self._ars_key = None
            self._sees_d = obs.stage_call(
                "pipeline.sees_materialize", _copy_slab_stage, self._anc_d
            )

    def _recompute_depth(self, w_used: int) -> None:
        d = self._depth_w
        par = self._parents_w
        for i in range(w_used):
            sp = par[i, 0]
            d[i] = 1 + (d[sp] if sp >= 0 else 0)
        if int(d[:w_used].max(initial=0)) > self._chain_cap:
            self._chain_cap = _bucket(int(d[:w_used].max()), 32)

    def _fork_pairs_padded(self) -> np.ndarray:
        g = self._fork_np.shape[0]
        if g > self._g_cap:
            self._g_cap = _bucket(g, 8)
        out = np.full((self._g_cap, 3), -1, np.int32)
        out[:g] = self._fork_np
        return out

    # ----------------------------------------------------- column store

    def _add_columns(self, events: List[int]) -> None:
        if not events:
            return
        # coarse grain for the same reason as the batch path: one padded
        # width per pass keeps the block kernel + donated update on a
        # single jit signature (padded cols are -1 -> masked -> exact)
        batch = _bucket(len(events), 64)
        if self._n_cols + batch > self._wcol_cap:
            new_cap = self._next_col_cap(
                self._n_cols, batch, self._wcol_cap
            )
            self._ssm_d = jnp.pad(
                self._ssm_d, ((0, 0), (0, new_cap - self._wcol_cap))
            )
            ce = np.full((new_cap,), -1, np.int32)
            ce[: self._wcol_cap] = self._col_events
            self._col_events = ce
            self._wcol_cap = new_cap
        cols_arr = np.full((batch,), -1, np.int32)
        cols_arr[: len(events)] = events
        # suffix cut: rows below the earliest new witness can never
        # strongly-see it (the slab already holds their exact value, zero)
        if (
            self._cache_blocks
            and self._ars_cache is not None
            and min(events) >= self._ars_key[0]
        ):
            # pass-local fast path: every new witness is a new row, so the
            # pass's cached a-side gather already covers the suffix
            key0, key_rows = self._ars_key
            off, rows_eff = _suffix_rows(
                key0 + key_rows, min(events), key_rows
            )
            row0 = off
            part = obs.stage_call(
                "pipeline.ssm_block_from_rows", ssm_block_from_rows_stage,
                self._ars_cache, self._sees_d, jnp.asarray(self._mt_np),
                jnp.asarray(self._stake), jnp.asarray(cols_arr),
                np.int32(off - key0), rows=rows_eff,
                tot_stake=self._tot, matmul_dtype_name=self._mm,
            )
        else:
            row0, rows_eff = _suffix_rows(
                self._rows_hi, min(events), self._w_pad
            )
            part = self._ssm_block_fn(
                self._sees_d, jnp.asarray(self._mt_np),
                jnp.asarray(self._stake), jnp.asarray(cols_arr),
                np.int32(row0), rows=rows_eff, tot_stake=self._tot,
                matmul_dtype_name=self._mm,
            )
        for j, e in enumerate(events):
            self._colpos_w[e] = self._n_cols + j
            self._col_events[self._n_cols + j] = e
        self._ssm_d = obs.stage_call(
            "pipeline.inc_ssm_update", update_block_stage,
            self._ssm_d, part, np.int32(row0), np.int32(self._n_cols),
        )
        self._n_cols += len(events)

    # ------------------------------------------------------- extend pass

    def _rounds_span_fixpoint(self, parents_d, creator_d, stake_d, n_valid,
                              has_forks, w0, n_pad_new, r_base_d):
        """Fused rounds scan: spans of up to ``self._fuse`` chunks per
        dispatch (``rounds_span_stage``), each run to a witness-column
        fixpoint.  Returns the accepted final carry (device tuple, same
        layout as the unfused loop's ``state``) or ``None`` on round/slot
        overflow — the caller rebases, which is exact because the unfused
        path also commits nothing once its sticky overflow bit is set.

        Exactness vs the per-chunk loop: every probe re-runs the whole
        span from the SAME host-mirror carry, and a probe is accepted
        only when every witness registered anywhere in its output table
        already had a strongly-sees column for the entire run.  A missing
        column deterministically reads as not-strongly-seen (the scan
        body masks ``col_pos < 0`` — under-promotion only, never
        garbage), so an accepted run never consumed a value the
        fully-informed run wouldn't produce; its outputs are therefore
        bit-identical to running the chunks one dispatch at a time.
        Each failed probe registers >= 1 event whose column is absent
        and ``_add_columns`` makes it present, so columns grow strictly
        monotonically and the loop terminates within span_len probes.
        A ragged tail (n_chunks % fuse != 0) gets its own static
        ``k_chunks`` — a session-bounded shape family (< fuse values).
        """
        chunk = self._chunk
        n_chunks = n_pad_new // chunk
        # host-side carry: every probe uploads fresh device buffers from
        # these, so the donated span stage (carry positions 6-10) never
        # consumes a buffer the retry loop still needs
        carry_h = (self._rnd_w, self._wits_w, self._tab_np, self._cnt_np)
        state = None
        ci = 0
        while ci < n_chunks:
            k = min(self._fuse, n_chunks - ci)
            start = np.int32(w0 + ci * chunk)
            span_len = k * chunk
            for _attempt in range(span_len + 1):
                out = obs.stage_call_fused(
                    "pipeline.rounds_span_stage", k, rounds_span_stage,
                    parents_d, self._ssm_d, jnp.asarray(self._colpos_w),
                    creator_d, stake_d, np.int32(n_valid),
                    jnp.asarray(carry_h[0]), jnp.asarray(carry_h[1]),
                    jnp.asarray(carry_h[2]), jnp.asarray(carry_h[3]),
                    jnp.zeros((), dtype=jnp.int32), start, r_base_d,
                    tot_stake=self._tot, r_max=self._r_cap,
                    s_max=self._s_cap, has_forks=has_forks,
                    chunk=chunk, k_chunks=k,
                )
                tab = obs.to_host(out[2])
                registered = np.unique(tab[tab >= 0])
                missing = registered[self._colpos_w[registered] < 0]
                if missing.size == 0:
                    state = out
                    break
                self._add_columns([int(e) for e in missing])
            else:
                raise RuntimeError("witness-column span did not converge")
            if int(obs.to_host(state[4])):
                return None
            ci += k
            if ci < n_chunks:
                # next span resumes from this span's accepted carry; pull
                # it to host ONCE per span (copy=True: an owned host
                # array, never a zero-copy view of the device buffer the
                # next probe would donate)
                carry_h = (
                    obs.to_host(state[0], copy=True),
                    obs.to_host(state[1], copy=True),
                    obs.to_host(state[2], copy=True),
                    obs.to_host(state[3], copy=True),
                )
        return state

    def _extend_pass(self, n_new: int) -> Tuple[List[int], bool]:
        """One incremental pass over the ``n_new`` freshly packed events.
        Returns ``(newly_ordered, need_rebase)``."""
        p = self.packer
        lo = self._lo
        w0 = self._n_done - lo
        n1 = len(p)
        chunk = self._chunk
        n_pad_new = _bucket(n_new, chunk)
        self._ensure_row_capacity(w0 + n_pad_new)
        sl = slice(w0, w0 + n_new)
        gsl = slice(self._n_done, n1)
        par, creator_new, coin_new, t_new = p.window_view(self._n_done, n1)
        parw = np.where(par >= 0, par - lo, -1).astype(np.int32)
        self._parents_w[sl] = parw
        self._creator_w[sl] = creator_new
        self._coin_w[sl] = coin_new
        self._t_w[sl] = t_new
        for j in range(n_new):
            sp = parw[j, 0]
            self._depth_w[w0 + j] = 1 + (self._depth_w[sp] if sp >= 0 else 0)
        dmax = int(self._depth_w[: w0 + n_new].max(initial=1))
        if dmax > self._chain_cap:
            self._chain_cap = _bucket(dmax, 32)
        # member-table slots for the new events (host bookkeeping only —
        # the ssm block kernel gathers straight from the sees slab)
        for j in range(n_new):
            m = int(creator_new[j])
            slot = int(self._mcount[m])
            if slot >= self._k_cap:
                self._grow_k(slot + 1)
            self._mt_np[m, slot] = w0 + j
            self._mcount[m] = slot + 1
        # fork pairs arriving with this delta (window-remapped)
        if p.n_fork_pairs > self._g_done:
            fp = p.fork_pairs_view(self._g_done)
            new_pairs = np.stack(
                [fp[:, 0], fp[:, 1] - lo, fp[:, 2] - lo], axis=1,
            ).astype(np.int32)
            was_forkless = self._fork_np.shape[0] == 0
            self._fork_np = np.concatenate([self._fork_np, new_pairs])
            self._g_done = p.n_fork_pairs
            if was_forkless:
                self._materialize_sees()
        has_forks = self._fork_np.shape[0] > 0

        parents_d = jnp.asarray(self._parents_w)
        creator_d = jnp.asarray(self._creator_w)
        stake_d = jnp.asarray(self._stake)
        n_valid = np.int32(w0 + n_new)

        # ---- device: one fused dispatch extends ancestry + sees, then one
        # ssm block call covers every new row x every live column (the
        # b-side gather happens once per pass, not once per chunk)
        b0 = w0 // self._block
        b1 = -(-(w0 + n_new) // self._block)
        if has_forks:
            self._anc_d, self._sees_d = obs.stage_call(
                "pipeline.inc_extend_vis",
                make_extend_visibility_forked_stage(self._kern),
                self._anc_d, self._sees_d, parents_d,
                jnp.asarray(self._fork_pairs_padded()), creator_d,
                np.int32(b0), np.int32(b1), np.int32(w0),
                block=self._block, rows=n_pad_new, n_members=self._m,
                matmul_dtype_name=self._mm,
            )
        else:
            self._anc_d = obs.stage_call(
                "pipeline.inc_extend_vis",
                make_extend_visibility_stage(self._kern),
                self._anc_d, parents_d, np.int32(b0), np.int32(b1),
                block=self._block, matmul_dtype_name=self._mm,
            )
            self._sees_d = self._anc_d
        mt_d = jnp.asarray(self._mt_np)
        # round-restricted column suffix: a new row i is only ever queried
        # against witness columns of round >= r0(i) - 1 — the rounds scan
        # asks for round == r0(i) and fame collects votes from the single
        # round below the voter — so columns whose witness round sits
        # entirely below min_i r0(i) - 1 can skip the extension matmul;
        # their block entries keep the slab value (zero), which no reader
        # ever queries for these rows.
        col_lo = 0
        if self._n_cols and n_new:
            lb = np.zeros((n_new,), np.int32)
            rw = self._rnd_w
            for j in range(n_new):
                p0, p1 = int(parw[j, 0]), int(parw[j, 1])
                b = 0
                if p0 >= 0:
                    b = int(rw[p0]) if p0 < w0 else int(lb[p0 - w0])
                if p1 >= 0:
                    b2 = int(rw[p1]) if p1 < w0 else int(lb[p1 - w0])
                    if b2 > b:
                        b = b2
                lb[j] = b
            min_lb = int(lb.min())
            if min_lb > 1:
                ce = self._col_events[: self._n_cols]
                qm = rw[np.clip(ce, 0, self._w_pad - 1)] >= min_lb - 1
                first = int(np.argmax(qm)) if qm.any() else self._n_cols
                # block-aligned so the shape family stays the one the
                # un-cut pass would compile anyway
                col_lo = (first // 256) * 256
        c_eff = min(
            self._wcol_cap - col_lo,
            _bucket(max(self._n_cols - col_lo, 1), 256),
        )
        cols_d = jnp.asarray(self._col_events[col_lo : col_lo + c_eff])
        if self._cache_blocks:
            # gather the new rows' a-side once; the pass's witness-column
            # adds reuse it (new witnesses are always new rows)
            self._ars_cache = obs.stage_call(
                "pipeline.ssm_gather_rows", ssm_gather_rows_stage,
                self._sees_d, mt_d, np.int32(w0), rows=n_pad_new,
            )
            self._ars_key = (w0, n_pad_new)
            part = obs.stage_call(
                "pipeline.ssm_block_from_rows", ssm_block_from_rows_stage,
                self._ars_cache, self._sees_d, mt_d, stake_d, cols_d,
                np.int32(0), rows=n_pad_new,
                tot_stake=self._tot, matmul_dtype_name=self._mm,
            )
        else:
            part = self._ssm_block_fn(
                self._sees_d, mt_d, stake_d, cols_d, np.int32(w0),
                rows=n_pad_new, tot_stake=self._tot,
                matmul_dtype_name=self._mm,
            )
        self._ssm_d = obs.stage_call(
            "pipeline.inc_ssm_update", update_block_stage,
            self._ssm_d, part, np.int32(w0), np.int32(col_lo),
        )
        self._rows_hi = w0 + n_pad_new

        # ---- resumed rounds scan over the new events only
        r_base_d = np.int32(self._r_base)
        if self._fuse > 1:
            state = self._rounds_span_fixpoint(
                parents_d, creator_d, stake_d, n_valid, has_forks,
                w0, n_pad_new, r_base_d,
            )
            if state is None:
                # round/slot capacity overflow mid-span -> rebase now;
                # the unfused path also commits nothing on overflow, so
                # skipping the remaining spans is exact
                return [], True
        else:
            state = (
                jnp.asarray(self._rnd_w),
                jnp.asarray(self._wits_w),
                jnp.asarray(self._tab_np),
                jnp.asarray(self._cnt_np),
                jnp.zeros((), dtype=jnp.int32),
            )
            for start in range(w0, w0 + n_pad_new, chunk):
                for _attempt in range(chunk + 1):
                    out = obs.stage_call(
                        "pipeline.rounds_chunk_stage", rounds_chunk_stage,
                        parents_d, self._ssm_d, jnp.asarray(self._colpos_w),
                        creator_d, stake_d, np.int32(n_valid), *state,
                        np.int32(start), r_base_d,
                        tot_stake=self._tot, r_max=self._r_cap,
                        s_max=self._s_cap, has_forks=has_forks, chunk=chunk,
                    )
                    tab = obs.to_host(out[2])
                    registered = np.unique(tab[tab >= 0])
                    missing = registered[self._colpos_w[registered] < 0]
                    if missing.size == 0:
                        state = out
                        break
                    rnd_np = obs.to_host(out[0])
                    ce = np.arange(start, start + chunk, dtype=np.int64)
                    pc = self._parents_w[ce]
                    r0 = np.where(
                        pc[:, 0] < 0,
                        -1,
                        np.maximum(rnd_np[np.maximum(pc[:, 0], 0)],
                                   rnd_np[np.maximum(pc[:, 1], 0)]),
                    )
                    affected = False
                    for w in missing:
                        if w < start:
                            affected = True
                            break
                        later = ce > w
                        if np.any(later & (r0 == rnd_np[w])):
                            affected = True
                            break
                    self._add_columns([int(e) for e in missing])
                    if not affected:
                        state = out
                        break
                else:
                    raise RuntimeError(
                        "witness-column chunk did not converge"
                    )

        # copy=True (np.array, not asarray): device pulls are read-only
        # views, and these mirrors are mutated in place by roll/prune
        rnd_w = obs.to_host(state[0], copy=True)
        wits_w = obs.to_host(state[1], copy=True)
        tab_np = obs.to_host(state[2], copy=True)
        cnt_np = obs.to_host(state[3], copy=True)
        if int(obs.to_host(state[4])):
            # round/slot capacity overflow -> rebase, which self-heals:
            # _columns_pass grows the flagged capacity and the adopted
            # window table inherits it (never a crash)
            return [], True
        # straggler guard: a witness below the frozen vote horizon could
        # change a committed tally — recompute from scratch instead
        wit_mask = wits_w[sl]
        if wit_mask.any():
            wr = rnd_w[sl][wit_mask]
            if int(wr.min()) < max(self._frozen_vote_hi,
                                   self._consensus_round):
                return [], True
        self._rnd_w = rnd_w
        self._wits_w = wits_w
        self._tab_np = tab_np
        self._cnt_np = cnt_np
        self._max_round = max(
            self._max_round, int(rnd_w[: w0 + n_new].max(initial=0))
        )
        self._grow_global(n1)
        self._round_g[gsl] = rnd_w[sl]
        self._wits_g[gsl] = wit_mask
        self._n_done = n1

        # ---- fame over the retained round window
        need = self._max_round - self._r_base + 3
        if need > self._r_fame:
            self._r_fame = min(self._r_cap, _bucket(need, 8))
        famous_d, dec_d = obs.stage_call(
            "pipeline.inc_fame", fame_window_stage,
            self._sees_d, self._ssm_d, jnp.asarray(self._colpos_w),
            state[2], creator_d, jnp.asarray(self._coin_w), stake_d,
            tot_stake=self._tot, coin_period=self.config.coin_period,
            r_max=self._r_fame, s_max=self._s_cap, has_forks=has_forks,
            matmul_dtype_name=self._mm,
        )
        fam = np.full((self._r_cap, self._s_cap), -1, np.int8)
        fam[: self._r_fame] = obs.to_host(famous_d).reshape(
            self._r_fame, self._s_cap
        )
        dec = np.full((self._r_cap, self._s_cap), -1, np.int32)
        dec[: self._r_fame] = obs.to_host(dec_d).reshape(
            self._r_fame, self._s_cap
        )
        self._famous_np = fam
        self._dec_np = dec

        # ---- order extraction for newly fame-complete rounds
        k_done = self._consensus_round - self._r_base
        ncomp = 0
        for k in range(self._r_cap):
            valid = self._tab_np[k] >= 0
            if self._cnt_np[k] <= 0:
                break
            if self._max_round < self._r_base + k + 2:
                break
            if (fam[k][valid] < 0).any():
                break
            ncomp = k + 1
        ordered_new: List[int] = []
        if ncomp > k_done:
            if ncomp > self._r_ord:
                self._r_ord = min(self._r_cap, _bucket(ncomp, 2))
            # the scan masks rounds past the fame-complete prefix, so its
            # cost window only needs to reach ncomp — not the historical
            # high-water mark (which still bounds the bucket family)
            r_ord_eff = min(self._r_ord, max(2, _bucket(ncomp, 2)))
            ts_unique, t_rank = np.unique(self._t_w, return_inverse=True)
            t_rank = t_rank.astype(np.int32).reshape(self._t_w.shape)
            rr_d, ts_d, recv_d = obs.stage_call(
                "pipeline.inc_order", order_window_stage,
                self._anc_d, state[2], state[3],
                jnp.asarray(fam.reshape(-1)), creator_d, parents_d[:, 0],
                jnp.asarray(t_rank),
                np.int32(self._max_round - self._r_base),
                np.int32(n_valid), jnp.asarray(self._recv_w),
                r_max=r_ord_eff, s_max=self._s_cap,
                chain=self._chain_cap,
            )
            rr_np = obs.to_host(rr_d)
            tsr_np = obs.to_host(ts_d)
            recv_np = obs.to_host(recv_d, copy=True)
            max_dec = self._frozen_vote_hi
            for k in range(k_done, ncomp):
                slots = self._tab_np[k]
                fam_events: List[int] = []
                for s in range(self._s_cap):
                    e = int(slots[s])
                    if e < 0:
                        continue
                    is_f = int(fam[k, s]) == 1
                    self._famous_committed[lo + e] = is_f
                    if is_f:
                        fam_events.append(e)
                    max_dec = max(max_dec, self._r_base + int(dec[k, s]))
                ufw = _unique_famous(fam_events, self._creator_w)
                whiten = _whiten_sigs(p.sig(lo + e) for e in ufw)
                entries = []
                for w in np.where(rr_np == k)[0]:
                    gi = lo + int(w)
                    cts = int(ts_unique[tsr_np[w]])
                    tie = crypto.hash_bytes(whiten + p.event_id(gi))
                    entries.append((cts, tie, gi))
                entries.sort(key=lambda x: (x[0], x[1]))
                for cts, _tie, gi in entries:
                    self._rr_g[gi] = self._r_base + k
                    self._cts_g[gi] = cts
                    self._order.append(gi)
                    ordered_new.append(gi)
            self._frozen_vote_hi = max_dec
            self._consensus_round = self._r_base + ncomp
            self._recv_w = recv_np

        # ---- advance the round window and prune the decided prefix
        dr = self._consensus_round - self._r_base
        if dr > 0:
            self._roll_rounds(dr)
        self._maybe_prune()
        return ordered_new, False

    def _roll_rounds(self, dr: int) -> None:
        self._on_roll(dr)

        def roll(a, fill):
            out = np.full_like(a, fill)
            out[:-dr] = a[dr:]
            return out

        self._tab_np = roll(self._tab_np, -1)
        self._cnt_np = roll(self._cnt_np, 0)
        self._famous_np = roll(self._famous_np, -1)
        self._dec_np = roll(self._dec_np, -1)
        self._r_base += dr
        self._maybe_compact_columns()

    def _live_col_mask(self) -> np.ndarray:
        """Which occupied column slots are still queryable: witness rounds
        at or above the committed round window (everything below can never
        be asked again — the straggler guard rebases first)."""
        ce = self._col_events[: self._n_cols]
        valid = ce >= 0
        return valid & (
            self._rnd_w[np.clip(ce, 0, self._w_pad - 1)] >= self._r_base
        )

    def _maybe_compact_columns(self) -> None:
        """Roll-time column compaction: columns of retired rounds keep
        padding every ssm block matmul until the next prune; once they
        outnumber a quarter of the store, gather the live columns left.
        Prune does the same compaction as part of its row shift."""
        live = self._live_col_mask()
        n_live = int(live.sum())
        stale = self._n_cols - n_live
        if stale < 256 or stale * 4 < self._n_cols:
            return
        keep = np.full((self._wcol_cap,), -1, np.int32)
        pos_live = np.where(live)[0]
        keep[: len(pos_live)] = pos_live
        kept_events = self._col_events[pos_live]
        self._ssm_d = obs.stage_call(
            "pipeline.inc_compact_cols", compact_cols_stage,
            self._ssm_d, jnp.asarray(keep),
        )
        self._colpos_w[:] = -1
        ce = np.full((self._wcol_cap,), -1, np.int32)
        ce[: len(kept_events)] = kept_events
        self._colpos_w[kept_events] = np.arange(
            len(kept_events), dtype=np.int32
        )
        self._col_events = ce
        self._n_cols = len(kept_events)

    # ------------------------------------------------------------- prune

    def _maybe_prune(self) -> None:
        w_used = self._n_done - self._lo
        if w_used == 0:
            return
        nr = ~self._recv_w[:w_used]
        d = int(np.argmax(nr)) if nr.any() else w_used
        if self._fork_np.shape[0]:
            d = min(d, int(self._fork_np[:, 1:].min()))
        if d < self._prune_min:
            return
        self._on_prune(d, w_used)
        self._ars_cache = self._ars_key = None
        ce = self._col_events[: self._n_cols]
        live = (
            (ce >= d)
            & (self._rnd_w[np.clip(ce, 0, self._w_pad - 1)] >= self._r_base)
        )
        pos_live = np.where(live)[0]
        keep = np.full((self._wcol_cap,), -1, np.int32)
        keep[: len(pos_live)] = pos_live
        kept_events = self._col_events[pos_live] - d
        if self._fork_np.shape[0]:
            self._anc_d, self._sees_d, self._ssm_d = obs.stage_call(
                "pipeline.inc_prune", prune_stage,
                self._anc_d, self._sees_d, self._ssm_d,
                np.int32(d), np.int32(w_used), jnp.asarray(keep),
            )
        else:
            self._anc_d, self._ssm_d = obs.stage_call(
                "pipeline.inc_prune", prune_noforks_stage,
                self._anc_d, self._ssm_d,
                np.int32(d), np.int32(w_used), jnp.asarray(keep),
            )
            self._sees_d = self._anc_d
        # host mirrors
        w2 = w_used - d
        pw = self._parents_w[d:w_used]
        self._parents_w[:w2] = np.where(pw >= d, pw - d, -1)
        self._parents_w[w2:] = -1

        def roll1(a, fill):
            a[:w2] = a[d:w_used]
            a[w2:] = fill

        roll1(self._creator_w, 0)
        roll1(self._coin_w, 0)
        roll1(self._t_w, 0)
        roll1(self._rnd_w, 0)
        roll1(self._wits_w, False)
        roll1(self._recv_w, False)
        self._recompute_depth(w2)
        # member table + fork pairs + witness table entries shift by d
        self._rebuild_member_table(w2)
        if self._fork_np.shape[0]:
            self._fork_np = np.stack(
                [self._fork_np[:, 0], self._fork_np[:, 1] - d,
                 self._fork_np[:, 2] - d], axis=1,
            )
        tv = self._tab_np >= 0
        self._tab_np = np.where(tv, self._tab_np - d, -1)
        # rebuilt column store positions
        self._colpos_w[:] = -1
        ce2 = np.full((self._wcol_cap,), -1, np.int32)
        ce2[: len(kept_events)] = kept_events
        self._colpos_w[kept_events] = np.arange(
            len(kept_events), dtype=np.int32
        )
        self._col_events = ce2
        self._n_cols = len(kept_events)
        self._lo += d
        self._rows_hi = w2

    # ------------------------------------------------------------ rebase

    def _rebase(self) -> List[int]:
        """Full-recompute fallback: run the batch columns pipeline over the
        whole packed DAG, commit its outputs, and lift the device
        intermediates into fresh carried-window state (then prune)."""
        packed = self.packer.pack()
        n = packed.n
        prev_ordered = len(self._order)
        # witness-slot capacity must match the window table (monotone)
        extras = (
            len(set(packed.fork_pairs[:, 2].tolist()))
            if len(packed.fork_pairs)
            else 0
        )
        self._s_cap = max(self._s_cap, self._m + extras + 1)
        arrays, statics, ts_unique = prepare_inputs(
            packed, self.config, block=self._block, s_max=self._s_cap,
            matmul_dtype_name=self._mm,
        )
        chain = statics["chain"]
        r_rounds = min(statics["r_max"], _bucket(chain + 1, 32))
        out, aux = _columns_pass(
            packed, self.config, arrays["parents"], arrays["creator"],
            arrays["t_rank"], arrays["coin"], arrays["stake"],
            arrays["member_table"],
            n=n, tot=self._tot, block=self._block, r_rounds=r_rounds,
            s_max=self._s_cap, chain=chain, matmul_dtype_name=self._mm,
            # default kernel -> None, so the batch pass keeps its own
            # per-pass a-side gather cache; only a custom backend
            # (mesh / Pallas) overrides the seam
            ssm_block_fn=None if self._cache_blocks else self._ssm_block_fn,
        )
        # adopt any self-healed capacities (overflow retries inside the
        # batch pass grow s_max/r_rounds; the carried window table must
        # match the batch table's slot shape)
        self._s_cap = max(self._s_cap, aux["s_max"])
        heals = int(aux["overflow_retries"])
        self.overflow_heals += heals
        if heals and self.flightrec is not None:
            oo = obs.current()
            self.flightrec.trigger(
                "overflow_heal", node=self.flightrec_label,
                detail={"retries": heals, "s_cap": self._s_cap},
                decided_frontier={
                    self.flightrec_label: {
                        "decided": prev_ordered,
                        "round": self._consensus_round,
                    },
                },
                registry=oo.registry if oo is not None else None,
            )
        result = finalize_order(packed, out, ts_unique)

        # ---- commit everything the batch pass decided
        self._grow_global(n)
        self._round_g[:n] = out["round"][:n]
        self._wits_g[:n] = out["is_witness"][:n]
        self._rr_g[:n] = result.round_received
        self._cts_g[:n] = result.consensus_ts
        self._order = list(result.order)
        self._max_round = int(out["max_round"])
        self._n_done = n
        self._g_done = packed.fork_pairs.shape[0]
        tabf = out["wit_table"]
        r_tight = tabf.shape[0]
        fam = out["famous"].reshape(r_tight, self._s_cap)
        dec = out["fame_decided_at"].reshape(r_tight, self._s_cap)
        cntf = out["wit_count"]
        cr = 0
        while cr < r_tight:
            valid = tabf[cr] >= 0
            if cntf[cr] <= 0 or self._max_round < cr + 2:
                break
            if (fam[cr][valid] < 0).any():
                break
            cr += 1
        self._consensus_round = cr
        self._famous_committed = {}
        fv = 0
        for r in range(cr):
            for s in range(self._s_cap):
                e = int(tabf[r, s])
                if e < 0:
                    continue
                self._famous_committed[e] = bool(fam[r, s] == 1)
                fv = max(fv, int(dec[r, s]))
        self._frozen_vote_hi = fv

        # ---- choose the pruned boundary and lift the window
        received = result.round_received >= 0
        nr = ~received
        lo = int(np.argmax(nr)) if nr.any() else n
        if packed.fork_pairs.shape[0]:
            lo = min(lo, int(packed.fork_pairs[:, 1:].min()))
        self._lo = lo
        self._r_base = cr
        self._on_rebase(packed, out, aux)
        w_used = n - lo
        self._w_pad = max(
            self._w_pad,
            _bucket(w_used + 2 * self._chunk, self._window_bucket),
        )
        r_need = self._max_round - cr + 16
        if r_need > self._r_cap:
            self._r_cap = _bucket(r_need, 16)
        w_pad = self._w_pad
        self._alloc_mirrors(w_pad)
        pg = packed.parents[lo:n].astype(np.int32)
        self._parents_w[:w_used] = np.where(pg >= lo, pg - lo, -1)
        self._creator_w[:w_used] = packed.creator[lo:n]
        self._coin_w[:w_used] = packed.coin[lo:n]
        self._t_w[:w_used] = packed.t[lo:n]
        self._rnd_w[:w_used] = out["round"][lo:n]
        self._wits_w[:w_used] = out["is_witness"][lo:n]
        self._recv_w[:w_used] = received[lo:]
        self._recompute_depth(w_used)
        # member table over the window
        self._rebuild_member_table(w_used)
        # fork pairs, window-remapped (all members >= lo by the cap above)
        if packed.fork_pairs.shape[0]:
            fp = packed.fork_pairs.astype(np.int32)
            self._fork_np = np.stack(
                [fp[:, 0], fp[:, 1] - lo, fp[:, 2] - lo], axis=1
            )
        else:
            self._fork_np = np.zeros((0, 3), np.int32)
        # witness table rows [cr, cr + r_cap), entries window-remapped
        self._tab_np = np.full((self._r_cap, self._s_cap), -1, np.int32)
        self._cnt_np = np.zeros((self._r_cap,), np.int32)
        self._famous_np = np.full((self._r_cap, self._s_cap), -1, np.int8)
        self._dec_np = np.full((self._r_cap, self._s_cap), -1, np.int32)
        hi = min(r_tight, cr + self._r_cap)
        rows = hi - cr
        if rows > 0:
            tw = tabf[cr:hi].astype(np.int32)
            self._tab_np[:rows] = np.where(tw >= 0, tw - lo, -1)
            self._cnt_np[:rows] = cntf[cr:hi]
            self._famous_np[:rows] = fam[cr:hi]
            self._dec_np[:rows] = dec[cr:hi]
        # column store: keep retained-round witness columns
        bat_pos = aux["col_pos"]
        bat_ssm = np.asarray(aux["ssm_c"])
        kept = [
            (e, int(bat_pos[e]))
            for e in range(lo, n)
            if bat_pos[e] >= 0 and int(out["round"][e]) >= cr
            and bool(out["is_witness"][e])
        ]
        n_cols = len(kept)
        self._wcol_cap = max(self._wcol_cap, _bucket(n_cols + 128, 256))
        ssm_w = np.zeros((w_pad, self._wcol_cap), bool)
        self._col_events = np.full((self._wcol_cap,), -1, np.int32)
        if kept:
            pos_list = [pos for _e, pos in kept]
            ssm_w[:w_used, :n_cols] = bat_ssm[lo:n][:, pos_list]
            for j, (e, _pos) in enumerate(kept):
                self._col_events[j] = e - lo
                self._colpos_w[e - lo] = j
        self._n_cols = n_cols
        # visibility slabs, window-sliced (sees aliases anc while fork-free)
        bat_anc = np.asarray(aux["anc"])
        anc_w = np.zeros((w_pad, w_pad), bool)
        anc_w[:w_used, :w_used] = bat_anc[lo:n, lo:n]
        self._anc_d = self._put(anc_w)
        if packed.fork_pairs.shape[0]:
            bat_sees = np.asarray(aux["sees"])
            sees_w = np.zeros((w_pad, w_pad), bool)
            sees_w[:w_used, :w_used] = bat_sees[lo:n, lo:n]
            self._sees_d = self._put(sees_w)
        else:
            self._sees_d = self._anc_d
        self._ssm_d = self._put(ssm_w)
        self._rows_hi = w_used
        self._ars_cache = self._ars_key = None
        self._initialized = True
        return self._order[prev_ordered:]
