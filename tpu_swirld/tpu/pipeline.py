"""The batched device consensus pipeline (JAX / XLA).

This is the TPU-native replacement for the oracle's per-event recursion
(``Node.divide_rounds`` / ``decide_fame`` / ``find_order`` — SURVEY.md §2
#6-8, BASELINE.json north star).  It consumes a :class:`~tpu_swirld.packing.
PackedDAG` and produces **bit-identical** ``round`` / ``is_witness`` /
``famous`` / ``(round_received, consensus_ts)`` outputs; the final total
order additionally applies the signature-whitened hash tiebreak, which is a
host-side byte operation (``run_consensus``).

Phase structure (each phase a pure jittable function; ``consensus_arrays``
fuses them into one jit for the end-to-end device step):

1. ``ancestry`` — reflexive-transitive parent closure as a *blockwise*
   boolean matmul: events are processed in topological blocks; each block's
   internal closure is log2(B) squarings of a B×B adjacency (MXU), then one
   (B×B)@(B×N) matmul propagates the external parent rows.  This is the
   "tiled boolean matrix-power reachability" kernel of SURVEY §5.
2. ``forkseen_matrix`` / ``sees_matrix`` — fork-aware visibility.  Fork
   pairs (same creator+seq, packed on host) poison descendants: ``sees(x,y)
   = anc(x,y) & ~forkseen(x, creator(y))``.
3. ``ssm_matrix`` — strongly-sees via the ∃-z member hop: per member m,
   ``hit_m = (S[:, events_m] @ S[events_m, :]) > 0``; stake-weighted count
   of hitting members crosses the strict-2/3 integer threshold.  Exactly
   the oracle's ``strongly_sees`` (∃-z rule).
4. ``rounds_scan`` — ``lax.scan`` over events in topo order carrying the
   round->witness-slot table: round = max(parent rounds) + promotion,
   witness = first-of-creator-in-round.
5. ``fame_scan`` — ``lax.scan`` over rounds carrying the previous round's
   vote matrix: direct votes at distance 1, stake tallies over strongly-
   seen previous-round witnesses (per-creator OR when forks exist), coin
   rounds take the packed signature middle bit; fame is decided by the
   chronologically first supermajority in a non-coin round.
6. ``order_scan`` — per fame-complete round: unique famous witnesses, the
   all-UFW ancestry test for round-received, and a self-parent chain walk
   producing each UFW's earliest-seeing timestamp; consensus timestamp is
   the lower median.

All supermajorities are exact integer tests ``3*amount > 2*total``.  The
device stays int32-pure: int64 timestamps are dense-ranked on the host
(equal timestamps -> equal ranks, so lower-median selection is exact) and
the median *rank* is mapped back to the int64 value after the kernel.  Bool
matmuls run in ``matmul_dtype`` (bfloat16 on TPU — products are 0/1 and the
MXU accumulates in f32, so counts below 2^24 are exact; float32 on CPU) and
threshold at 0.5.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import math
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from tpu_swirld import crypto, obs
from tpu_swirld.config import SwirldConfig
from tpu_swirld.oracle.node import xor_bytes
from tpu_swirld.packing import PackedDAG

INT32_MAX = np.iinfo(np.int32).max


def _maybe_span(o, name: str, **args):
    """A tracer span under the ambient Obs, or a no-op when disabled.

    Stage-granular only — never called per event, so the disabled path
    costs one None check per *stage*."""
    if o is None:
        return contextlib.nullcontext()
    return o.tracer.span(name, **args)


def _record_shapes(o, *, n: int, n_pad: int, statics: Dict) -> None:
    """Pad-waste + static-shape gauges for one pipeline invocation."""
    g = o.registry
    g.gauge("pipeline_events").set(n)
    g.gauge("pipeline_pad_events").set(n_pad - n)
    g.gauge("pipeline_pad_waste_frac").set(
        round((n_pad - n) / max(n_pad, 1), 6)
    )
    g.gauge("pipeline_s_max").set(statics["s_max"])
    g.gauge("pipeline_block").set(statics["block"])
    # pipeline_r_max is set later, once the chain-trimmed effective bound
    # (the one the witness table actually uses) is known


def default_matmul_dtype():
    return jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16


def _bucket(v: int, m: int) -> int:
    """Round up to a multiple of m (recompile hygiene for static shapes)."""
    return ((max(v, 1) + m - 1) // m) * m


def _bmm(a: jnp.ndarray, b: jnp.ndarray, dtype) -> jnp.ndarray:
    """Boolean matmul: OR over products of 0/1 values (exact: f32 accum)."""
    return (
        jnp.matmul(
            a.astype(dtype), b.astype(dtype), preferred_element_type=jnp.float32
        )
        > 0.5
    )


# --------------------------------------------------------------- phase 1


def ancestry(parents: jnp.ndarray, *, block: int, matmul_dtype) -> jnp.ndarray:
    """Reflexive-transitive closure of the parent relation.

    ``parents`` int32[N, 2] with -1 for genesis, topologically ordered
    (parents strictly below), N a multiple of ``block``.  Returns bool[N, N]
    with ``anc[i, j]`` = "j is an ancestor of i" (reflexive).
    """
    n = parents.shape[0]
    assert n % block == 0, "pad N to a multiple of block"
    n_blocks = n // block
    n_sq = max(1, math.ceil(math.log2(block)))

    eye = jnp.eye(block, dtype=bool)
    jj = jnp.arange(block)

    def body(k, r):
        s = k * block
        pb = lax.dynamic_slice(parents, (s, 0), (block, 2))      # B,2
        local = pb - s                                           # in-block offset
        adj = (local[:, 0:1] == jj[None, :]) | (local[:, 1:2] == jj[None, :])
        lc = adj | eye
        for _ in range(n_sq):                                    # static unroll
            lc = lc | _bmm(lc, lc, matmul_dtype)
        pc = jnp.clip(pb, 0, n - 1)
        ext = pb >= 0                                            # external iff < s,
        ext = ext & (pb < s)                                     # in-block handled by lc
        g = (r[pc[:, 0]] & ext[:, 0:1]) | (r[pc[:, 1]] & ext[:, 1:2])   # B,N
        rows = _bmm(lc, g, matmul_dtype)                         # B,N
        diag = lax.dynamic_slice(rows, (0, s), (block, block)) | lc
        rows = lax.dynamic_update_slice(rows, diag, (0, s))
        return lax.dynamic_update_slice(r, rows, (s, 0))

    r0 = jnp.zeros((n, n), dtype=bool)
    return lax.fori_loop(0, n_blocks, body, r0)


# --------------------------------------------------------------- phase 2


def forkseen_matrix(
    anc: jnp.ndarray, fork_pairs: jnp.ndarray, n_members: int, matmul_dtype
) -> jnp.ndarray:
    """bool[N, M]: does x have a fork pair by member m among its ancestors?

    ``fork_pairs`` int32[G, 3] rows (member, idx_a, idx_b); G may include
    padding rows with member = -1.
    """
    n = anc.shape[0]
    if fork_pairs.shape[0] == 0:
        return jnp.zeros((n, n_members), dtype=bool)
    mcol = fork_pairs[:, 0]
    a = jnp.clip(fork_pairs[:, 1], 0, n - 1)
    b = jnp.clip(fork_pairs[:, 2], 0, n - 1)
    hit = anc[:, a] & anc[:, b] & (mcol >= 0)[None, :]           # N,G
    onehot = mcol[:, None] == jnp.arange(n_members)[None, :]     # G,M
    return _bmm(hit, onehot, matmul_dtype)


def sees_matrix(
    anc: jnp.ndarray, forkseen: jnp.ndarray, creator: jnp.ndarray
) -> jnp.ndarray:
    """Fork-aware visibility: sees(x, y) = anc(x, y) & ~forkseen(x, c(y))."""
    return anc & ~forkseen[:, creator]


# --------------------------------------------------------------- phase 3


def ssm_matrix(
    sees: jnp.ndarray,
    member_table: jnp.ndarray,
    stake: jnp.ndarray,
    tot_stake: int,
    matmul_dtype,
) -> jnp.ndarray:
    """Strongly-sees matrix (∃-z rule): bool[N, N].

    ``ssm[x, w]`` = members holding a strict 2/3 stake supermajority each
    have an event z with sees(x, z) and sees(z, w).
    """
    n = sees.shape[0]
    n_members, k = member_table.shape

    def body(m, acc):
        idx = member_table[m]                        # K
        valid = idx >= 0
        idxc = jnp.clip(idx, 0, n - 1)
        a = sees[:, idxc] & valid[None, :]           # N,K  (x sees z)
        b = sees[idxc, :] & valid[:, None]           # K,N  (z sees w)
        hit = _bmm(a, b, matmul_dtype)               # N,N
        return acc + stake[m] * hit.astype(jnp.int32)

    acc = lax.fori_loop(0, n_members, body, jnp.zeros((n, n), dtype=jnp.int32))
    return 3 * acc > 2 * tot_stake
# --------------------------------------------------------------- phase 4


def rounds_scan(
    parents: jnp.ndarray,
    ssm: jnp.ndarray,
    creator: jnp.ndarray,
    stake: jnp.ndarray,
    tot_stake: int,
    n_valid: jnp.ndarray,
    *,
    r_max: int,
    s_max: int,
    has_forks: bool,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Round assignment + witness registration (topo-order scan).

    Returns (round int32[N], is_witness bool[N], wit_table int32[r_max,
    s_max], wit_count int32[r_max], overflow bool[]).  Slot order within a
    round is registration (= topo) order, as in the oracle.  (The
    column-restricted variant runs via ``rounds_chunk_stage`` /
    ``_make_rounds_step`` with a ``col_pos`` map.)
    """
    step = _make_rounds_step(
        parents, ssm, creator, stake, tot_stake, n_valid,
        r_max=r_max, s_max=s_max, has_forks=has_forks, col_pos=None,
    )
    n = parents.shape[0]
    carry0 = (
        jnp.zeros((n,), dtype=jnp.int32),
        jnp.zeros((n,), dtype=bool),
        jnp.full((r_max, s_max), -1, dtype=jnp.int32),
        jnp.zeros((r_max,), dtype=jnp.int32),
        jnp.zeros((), dtype=bool),
    )
    (rnd, wits, tab, cnt, overflow), _ = lax.scan(
        step, carry0, jnp.arange(n)
    )
    return rnd, wits, tab, cnt, overflow


def _make_rounds_step(parents, ssm, creator, stake, tot_stake, n_valid, *,
                      r_max, s_max, has_forks, col_pos):
    """The shared per-event body of the rounds scan.  Carry:
    (rnd[N], wits[N], wit_table, wit_count, overflow)."""
    n = parents.shape[0]
    n_members = stake.shape[0]
    marange = jnp.arange(n_members)

    def step(carry, i):
        rnd, wits, tab, cnt, overflow = carry
        p1 = parents[i, 0]
        p2 = parents[i, 1]
        genesis = p1 < 0
        p1c = jnp.maximum(p1, 0)
        p2c = jnp.maximum(p2, 0)
        r0 = jnp.maximum(rnd[p1c], rnd[p2c])
        r0c = jnp.clip(r0, 0, r_max - 1)
        widx = tab[r0c]                                     # S
        wvalid = widx >= 0
        widxc = jnp.clip(widx, 0, n - 1)
        if col_pos is None:
            ss = ssm[i, widxc] & wvalid                     # S
        else:
            wpos = col_pos[widxc]                           # S (-1 = absent)
            ss = (
                ssm[i, jnp.clip(wpos, 0, ssm.shape[1] - 1)]
                & (wpos >= 0)
                & wvalid
            )
        if has_forks:
            wcre = creator[widxc]
            contrib = ((wcre[:, None] == marange[None, :]) & ss[:, None]).any(0)
            amount = jnp.sum(stake * contrib)
        else:
            # no forks packed -> at most one witness per (creator, round)
            amount = jnp.sum(stake[creator[widxc]] * ss)
        promoted = 3 * amount > 2 * tot_stake
        r = jnp.where(genesis, 0, r0 + promoted)
        is_wit = (genesis | (r > rnd[p1c])) & (i < n_valid)
        overflow = overflow | (is_wit & (r >= r_max))
        rc = jnp.clip(r, 0, r_max - 1)
        slot = cnt[rc]
        overflow = overflow | (is_wit & (slot >= s_max))
        do = is_wit & (slot < s_max) & (r < r_max)
        slotc = jnp.clip(slot, 0, s_max - 1)
        tab = tab.at[rc, slotc].set(jnp.where(do, i, tab[rc, slotc]))
        cnt = cnt.at[rc].add(do.astype(jnp.int32))
        rnd = rnd.at[i].set(jnp.where(i < n_valid, r, 0))
        wits = wits.at[i].set(is_wit)
        return (rnd, wits, tab, cnt, overflow), None

    return step


# --------------------------------------------------------------- phase 5


def fame_scan(
    wit_table: jnp.ndarray,
    sees: jnp.ndarray,
    ssm: jnp.ndarray,
    creator: jnp.ndarray,
    coin: jnp.ndarray,
    stake: jnp.ndarray,
    tot_stake: int,
    coin_period: int,
    matmul_dtype,
    *,
    has_forks: bool,
    col_pos: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Virtual fame voting.  Returns famous int8[r_max*s_max] over global
    witness slots (row-major (round, slot)): 1 famous, 0 not, -1 undecided.

    With ``col_pos``, ``ssm`` is column-restricted (every queried column is
    a witness, so the map is total here — guaranteed by the host loop).
    """
    r_max, s_max = wit_table.shape
    n = sees.shape[0]
    n_members = stake.shape[0]
    w_max = r_max * s_max
    # The fast tally multiplies stake values into a float32 matmul; that is
    # exact only while every sum stays below 2^24.  Forks additionally need
    # the per-creator OR.  Otherwise take the int32 per-creator path.
    exact_tally = has_forks or tot_stake >= (1 << 24)

    x_event = wit_table.reshape(-1)                     # W
    x_valid = x_event >= 0
    xe = jnp.clip(x_event, 0, n - 1)
    x_round = jnp.arange(w_max, dtype=jnp.int32) // s_max
    marange = jnp.arange(n_members)

    def step(carry, ry):
        v_prev, famous = carry                          # bool[S,W], int8[W]
        y_idx = wit_table[ry]                           # S
        y_valid = y_idx >= 0
        ye = jnp.clip(y_idx, 0, n - 1)
        d = ry - x_round                                # W
        sees_yx = sees[ye][:, xe] & y_valid[:, None] & x_valid[None, :]
        p_idx = wit_table[ry - 1]
        p_valid = p_idx >= 0
        pe = jnp.clip(p_idx, 0, n - 1)
        if col_pos is None:
            ssy = ssm[ye][:, pe]                        # S,S
        else:
            ppos = col_pos[pe]
            ssy = (
                ssm[ye][:, jnp.clip(ppos, 0, ssm.shape[1] - 1)]
                & (ppos >= 0)[None, :]
            )
        ssy = ssy & y_valid[:, None] & p_valid[None, :]
        pcre = creator[pe]                              # S
        pstake = jnp.where(p_valid, stake[pcre], 0)
        if exact_tally:
            # per-creator OR before stake-weighting (forked creators may
            # have several witnesses in round ry-1)
            onehot = (pcre[:, None] == marange[None, :]) & p_valid[:, None]
            w1 = (ssy[:, None, :] & onehot.T[None, :, :]).reshape(
                s_max * n_members, s_max
            )                                           # (S*M),S
            yes_c = _bmm(w1, v_prev, matmul_dtype).reshape(
                s_max, n_members, w_max
            )
            no_c = _bmm(w1, ~v_prev & p_valid[:, None], matmul_dtype).reshape(
                s_max, n_members, w_max
            )
            yes = jnp.sum(yes_c * stake[None, :, None], axis=1)     # S,W int32
            no = jnp.sum(no_c * stake[None, :, None], axis=1)
        else:
            sw = ssy * pstake[None, :]                  # S,S int32
            yes = jnp.matmul(
                sw.astype(jnp.float32),
                v_prev.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            ).astype(jnp.int32)
            no = jnp.matmul(
                sw.astype(jnp.float32),
                (~v_prev & p_valid[:, None]).astype(jnp.float32),
                preferred_element_type=jnp.float32,
            ).astype(jnp.int32)
        v_tally = yes >= no                             # S,W
        super_ = 3 * jnp.maximum(yes, no) > 2 * tot_stake
        is_coin = (d % coin_period) == 0                # W
        coin_y = (coin[ye] > 0)[:, None]                # S,1
        vote = jnp.where(
            (d == 1)[None, :],
            sees_yx,
            jnp.where(is_coin[None, :], jnp.where(super_, v_tally, coin_y), v_tally),
        )
        vote = vote & y_valid[:, None] & x_valid[None, :] & (d >= 1)[None, :]
        eligible = (
            super_
            & y_valid[:, None]
            & (x_valid & (d >= 2) & ~is_coin)[None, :]
        )
        any_dec = eligible.any(0)                       # W
        first_y = jnp.argmax(eligible, axis=0)          # W
        val = v_tally[first_y, jnp.arange(w_max)]
        famous = jnp.where(
            (famous < 0) & any_dec, val.astype(jnp.int8), famous
        )
        return (vote, famous), None

    carry0 = (
        jnp.zeros((s_max, w_max), dtype=bool),
        jnp.full((w_max,), -1, dtype=jnp.int8),
    )
    (v_last, famous), _ = lax.scan(
        step, carry0, jnp.arange(1, r_max, dtype=jnp.int32)
    )
    return famous


# --------------------------------------------------------------- phase 6


def order_scan(
    anc: jnp.ndarray,
    wit_table: jnp.ndarray,
    wit_count: jnp.ndarray,
    famous: jnp.ndarray,
    creator: jnp.ndarray,
    self_parent: jnp.ndarray,
    t_rank: jnp.ndarray,
    max_round: jnp.ndarray,
    n_valid: jnp.ndarray,
    *,
    chain: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Round-received + consensus timestamp ranks.

    Processes the maximal fame-complete prefix of rounds in ascending
    order; an event is received in the first round whose unique famous
    witnesses all have it as an ancestor; its consensus timestamp is the
    lower median of the UFWs' earliest-seeing self-ancestor timestamps
    (as dense ranks — the host maps ranks back to int64 values).
    Returns (round_received int32[N] (-1 = not received), ts_rank int32[N]).
    """
    r_max, s_max = wit_table.shape
    n = anc.shape[0]
    famous_grid = famous.reshape(r_max, s_max)

    wvalid = wit_table >= 0
    decided = (famous_grid >= 0) | ~wvalid
    complete = decided.all(axis=1) & (
        max_round >= jnp.arange(r_max) + 2
    ) & (wit_count > 0)
    # maximal prefix of fame-complete rounds (cumulative AND)
    prefix = jnp.cumprod(complete.astype(jnp.int32)) > 0

    ev_valid = jnp.arange(n) < n_valid

    def step(carry, r):
        received, rr_out, ts_out = carry
        widx = wit_table[r]
        valid = widx >= 0
        we = jnp.clip(widx, 0, n - 1)
        fam = (famous_grid[r] == 1) & valid             # S
        wcre = creator[we]
        # count famous witnesses per creator via pairwise same-creator sum
        same = (wcre[:, None] == wcre[None, :]) & valid[:, None] & valid[None, :]
        cnt_same = jnp.sum(same & fam[None, :], axis=1)  # S: per slot, count of
        ufw = fam & (cnt_same == 1)                      # famous by same creator
        has = ufw.any()
        anc_rows = anc[we]                               # S,N
        all_see = (anc_rows | ~ufw[:, None]).all(0)      # N
        newly = (
            all_see & ~received & prefix[r] & has & ev_valid
        )
        # earliest-seeing timestamps via self-chain walk (w -> genesis)
        def walk(c2, _):
            cur, tsw = c2
            an = anc[cur]                                # S,N
            tsw = jnp.where(an, t_rank[cur][:, None], tsw)
            nxt = self_parent[cur]
            cur = jnp.where(nxt >= 0, nxt, cur)
            return (cur, tsw), None

        ts0 = jnp.full((s_max, n), INT32_MAX, dtype=jnp.int32)
        (cur, tsw), _ = lax.scan(walk, (we, ts0), None, length=chain)
        tsw = jnp.where(ufw[:, None], tsw, INT32_MAX)    # mask non-UFW rows
        ts_sorted = jnp.sort(tsw, axis=0)                # S,N ascending
        nv = jnp.sum(ufw)
        med_i = jnp.clip((nv - 1) // 2, 0, s_max - 1)
        med = ts_sorted[med_i]                           # N
        rr_out = jnp.where(newly, r, rr_out)
        ts_out = jnp.where(newly, med, ts_out)
        received = received | newly
        return (received, rr_out, ts_out), None

    carry0 = (
        jnp.zeros((n,), dtype=bool),
        jnp.full((n,), -1, dtype=jnp.int32),
        jnp.zeros((n,), dtype=jnp.int32),
    )
    (received, rr_out, ts_out), _ = lax.scan(
        step, carry0, jnp.arange(r_max, dtype=jnp.int32)
    )
    return rr_out, ts_out


# ----------------------------------------------------------- fused kernel


def rounds_body(
    parents, creator, stake, fork_pairs, member_table, n_valid, *,
    tot_stake, block, r_max, s_max, has_forks, matmul_dtype_name,
    ssm_fn=None,
):
    """Stage A: ancestry -> sees -> strongly-sees -> rounds/witness scan.

    ``ssm_fn`` overrides the strongly-sees kernel (the FLOP bottleneck) —
    ``tpu_swirld.parallel`` passes the mesh-sharded version.  Jittable.
    """
    dt = jnp.bfloat16 if matmul_dtype_name == "bfloat16" else jnp.float32
    n_members = stake.shape[0]
    anc = ancestry(parents, block=block, matmul_dtype=dt)
    fseen = forkseen_matrix(anc, fork_pairs, n_members, dt)
    sees = sees_matrix(anc, fseen, creator)
    if ssm_fn is None:
        ssm = ssm_matrix(sees, member_table, stake, tot_stake, dt)
    else:
        ssm = ssm_fn(sees, member_table, stake, tot_stake, dt)
    rnd, wits, tab, cnt, overflow = rounds_scan(
        parents, ssm, creator, stake, tot_stake, n_valid,
        r_max=r_max, s_max=s_max, has_forks=has_forks,
    )
    max_round = jnp.max(jnp.where(jnp.arange(rnd.shape[0]) < n_valid, rnd, 0))
    return {
        "anc": anc, "sees": sees, "ssm": ssm, "round": rnd,
        "is_witness": wits, "wit_table": tab, "wit_count": cnt,
        "overflow": overflow, "max_round": max_round,
    }


def fame_order_body(
    anc, sees, ssm, wit_table, wit_count, creator, coin, stake, self_parent,
    t_rank, max_round, n_valid, *,
    tot_stake, coin_period, r_max, s_max, chain, has_forks,
    matmul_dtype_name,
):
    """Stage B: fame fixed point + order extraction over rounds [0, r_max)."""
    dt = jnp.bfloat16 if matmul_dtype_name == "bfloat16" else jnp.float32
    tab = wit_table[:r_max]
    cnt = wit_count[:r_max]
    famous = fame_scan(
        tab, sees, ssm, creator, coin, stake, tot_stake, coin_period, dt,
        has_forks=has_forks,
    )
    rr, cts_rank = order_scan(
        anc, tab, cnt, famous, creator, self_parent, t_rank, max_round,
        n_valid, chain=chain,
    )
    return {
        "famous": famous, "round_received": rr,
        "consensus_ts_rank": cts_rank,
    }


def consensus_body(
    parents,
    creator,
    t_rank,
    coin,
    stake,
    fork_pairs,
    member_table,
    n_valid,
    *,
    tot_stake: int,
    coin_period: int,
    block: int,
    r_max: int,
    s_max: int,
    chain: int,
    has_forks: bool,
    matmul_dtype_name: str,
    ssm_fn=None,
):
    """End-to-end device consensus: packed arrays -> all consensus outputs.

    Composes :func:`rounds_body` + :func:`fame_order_body` in one trace —
    the fused single-jit form used by the graft entry and the mesh path.
    ``run_consensus`` instead runs the two stages as separate jits so the
    second can be re-bound with a tight ``r_max``.
    """
    a = rounds_body(
        parents, creator, stake, fork_pairs, member_table, n_valid,
        tot_stake=tot_stake, block=block, r_max=r_max, s_max=s_max,
        has_forks=has_forks, matmul_dtype_name=matmul_dtype_name,
        ssm_fn=ssm_fn,
    )
    b = fame_order_body(
        a["anc"], a["sees"], a["ssm"], a["wit_table"], a["wit_count"],
        creator, coin, stake, parents[:, 0], t_rank, a["max_round"], n_valid,
        tot_stake=tot_stake, coin_period=coin_period, r_max=r_max,
        s_max=s_max, chain=chain, has_forks=has_forks,
        matmul_dtype_name=matmul_dtype_name,
    )
    return {
        "round": a["round"],
        "is_witness": a["is_witness"],
        "wit_table": a["wit_table"],
        "wit_count": a["wit_count"],
        "overflow": a["overflow"],
        "max_round": a["max_round"],
        **b,
    }


consensus_arrays = functools.partial(
    jax.jit,
    static_argnames=(
        "tot_stake",
        "coin_period",
        "block",
        "r_max",
        "s_max",
        "chain",
        "has_forks",
        "matmul_dtype_name",
    ),
)(consensus_body)

rounds_stage = functools.partial(
    jax.jit,
    static_argnames=(
        "tot_stake", "block", "r_max", "s_max", "has_forks",
        "matmul_dtype_name",
    ),
)(rounds_body)


# --- column-restricted strongly-sees path (default single-host execution):
# visibility once, then an iterated {ssm columns -> rounds scan} loop on the
# host until every registered witness has a column (exactness certificate),
# then fame/order with the position-mapped restricted matrix.


@functools.partial(
    jax.jit, static_argnames=("n_members", "block", "matmul_dtype_name")
)
def visibility_stage(parents, creator, fork_pairs, *, n_members, block,
                     matmul_dtype_name):
    dt = jnp.bfloat16 if matmul_dtype_name == "bfloat16" else jnp.float32
    anc = ancestry(parents, block=block, matmul_dtype=dt)
    fseen = forkseen_matrix(anc, fork_pairs, n_members, dt)
    sees = sees_matrix(anc, fseen, creator)
    return anc, sees


@functools.partial(jax.jit, static_argnames=())
def member_slabs(sees, member_table):
    """Pre-gathered per-member visibility slabs for the column kernel:
    A3[m] = "x sees z" for member m's events (N, K) and B3[m] = "z sees w"
    (K, N) — gathered from the N×N sees matrix exactly once."""
    n = sees.shape[0]
    idx = member_table.reshape(-1)
    valid = idx >= 0
    idxc = jnp.clip(idx, 0, n - 1)
    m, k = member_table.shape
    a3 = (sees[:, idxc] & valid[None, :]).reshape(n, m, k).transpose(1, 0, 2)
    b3 = (sees[idxc, :] & valid[:, None]).reshape(m, k, n)
    return a3, b3


@functools.partial(
    jax.jit, static_argnames=("tot_stake", "matmul_dtype_name")
)
def ssm_cols_stage(a3, b3, stake, cols, *, tot_stake, matmul_dtype_name):
    """Strongly-sees columns from pre-gathered slabs: one batched matmul
    (M, N, K) @ (M, K, C), per-member >0 threshold, int32 stake tally."""
    dt = jnp.bfloat16 if matmul_dtype_name == "bfloat16" else jnp.float32
    n = a3.shape[1]
    n_members = a3.shape[0]
    colsc = jnp.clip(cols, 0, n - 1)
    col_valid = cols >= 0
    b_cols = b3[:, :, colsc] & col_valid[None, None, :]      # M,K,C

    def body(m, acc):                     # per-member (N,K)@(K,C) hop; the
        hit = _bmm(a3[m], b_cols[m], dt)  # (N,C) tally never leaves VMEM/HBM
        return acc + stake[m] * hit.astype(jnp.int32)

    acc = lax.fori_loop(
        0, n_members, body,
        jnp.zeros((n, cols.shape[0]), dtype=jnp.int32),
    )
    return (3 * acc > 2 * tot_stake) & col_valid[None, :]


@functools.partial(
    jax.jit,
    static_argnames=("tot_stake", "r_max", "s_max", "has_forks", "chunk"),
)
def rounds_chunk_stage(parents, ssm_c, col_pos, creator, stake, n_valid,
                       rnd, wits, tab, cnt, overflow, start, *,
                       tot_stake, r_max, s_max, has_forks, chunk):
    """One chunk of the rounds scan: events [start, start+chunk) resume
    from the carried (rnd, wits, tab, cnt, overflow) state.  Shares the
    per-event body with rounds_scan — used by the incremental
    column-restricted path."""
    step = _make_rounds_step(
        parents, ssm_c, creator, stake, tot_stake, n_valid,
        r_max=r_max, s_max=s_max, has_forks=has_forks, col_pos=col_pos,
    )
    carry0 = (rnd, wits, tab, cnt, overflow)
    (rnd, wits, tab, cnt, overflow), _ = lax.scan(
        step, carry0, start + jnp.arange(chunk)
    )
    return rnd, wits, tab, cnt, overflow


@functools.partial(
    jax.jit,
    static_argnames=(
        "tot_stake", "coin_period", "r_max", "s_max", "chain", "has_forks",
        "matmul_dtype_name",
    ),
)
def fame_order_cols_stage(
    anc, sees, ssm_c, col_pos, wit_table, wit_count, creator, coin, stake,
    self_parent, t_rank, max_round, n_valid, *,
    tot_stake, coin_period, r_max, s_max, chain, has_forks,
    matmul_dtype_name,
):
    dt = jnp.bfloat16 if matmul_dtype_name == "bfloat16" else jnp.float32
    tab = wit_table[:r_max]
    cnt = wit_count[:r_max]
    famous = fame_scan(
        tab, sees, ssm_c, creator, coin, stake, tot_stake, coin_period, dt,
        has_forks=has_forks, col_pos=col_pos,
    )
    rr, cts_rank = order_scan(
        anc, tab, cnt, famous, creator, self_parent, t_rank, max_round,
        n_valid, chain=chain,
    )
    return {
        "famous": famous, "round_received": rr,
        "consensus_ts_rank": cts_rank,
    }

_pallas_rounds_stages = {}


def rounds_stage_pallas(interpret: bool):
    """rounds_stage with the strongly-sees phase as the Pallas kernel."""
    fn = _pallas_rounds_stages.get(interpret)
    if fn is None:
        from tpu_swirld.tpu.pallas_kernels import make_ssm_fn

        fn = functools.partial(
            jax.jit,
            static_argnames=(
                "tot_stake", "block", "r_max", "s_max", "has_forks",
                "matmul_dtype_name",
            ),
        )(functools.partial(rounds_body, ssm_fn=make_ssm_fn(interpret=interpret)))
        _pallas_rounds_stages[interpret] = fn
    return fn

fame_order_stage = functools.partial(
    jax.jit,
    static_argnames=(
        "tot_stake", "coin_period", "r_max", "s_max", "chain", "has_forks",
        "matmul_dtype_name",
    ),
)(fame_order_body)


# ------------------------------------------------------- host orchestration


@dataclasses.dataclass
class ConsensusResult:
    """Host-side view of the device outputs (indices into the PackedDAG)."""

    n: int
    round: np.ndarray            # int32[n]
    is_witness: np.ndarray       # bool[n]
    famous: Dict[int, Optional[bool]]   # witness idx -> fame (None undecided)
    round_received: np.ndarray   # int32[n] (-1 not received)
    consensus_ts: np.ndarray     # int64[n]
    order: List[int]             # final total order (packed indices)
    max_round: int
    timings: Dict[str, float] = dataclasses.field(default_factory=dict)


def _pad_packed(packed: PackedDAG, block: int):
    n = packed.n
    n_pad = ((n + block - 1) // block) * block
    pad = n_pad - n

    def padi(a, fill):
        if pad == 0:
            return a
        shape = (pad,) + a.shape[1:]
        return np.concatenate([a, np.full(shape, fill, a.dtype)], axis=0)

    parents = padi(packed.parents, -1)
    creator = padi(packed.creator, 0)
    seq = padi(packed.seq, 0)
    t = padi(packed.t, 0)
    coin = padi(packed.coin, 0)
    return n_pad, parents, creator, seq, t, coin


def prepare_inputs(
    packed: PackedDAG,
    config: Optional[SwirldConfig] = None,
    *,
    block: int = 128,
    r_max: Optional[int] = None,
    s_max: Optional[int] = None,
    matmul_dtype_name: Optional[str] = None,
):
    """Host prep shared by :func:`run_consensus` and the graft entry:
    block padding, dense timestamp ranks, and the static shape parameters.

    Returns ``(arrays, statics, ts_unique)`` where ``arrays`` holds the
    numpy kernel inputs (keys match the kernel's positional order:
    parents, creator, t_rank, coin, stake, fork_pairs, member_table,
    n_valid) and ``statics`` the keyword shape parameters.
    """
    config = config or SwirldConfig(n_members=packed.n_members)
    if matmul_dtype_name is None:
        matmul_dtype_name = (
            "float32" if jax.default_backend() == "cpu" else "bfloat16"
        )
    n = packed.n
    _n_pad, parents, creator, _seq, t, coin = _pad_packed(packed, block)
    extras = (
        len(set(packed.fork_pairs[:, 2].tolist()))
        if len(packed.fork_pairs)
        else 0
    )
    if s_max is None:
        s_max = packed.n_members + extras + 1
    if r_max is None:
        r_max = int(config.max_rounds)
    chain = int(packed.seq.max()) + 1 if n else 1
    # dense-rank timestamps so the device stays int32-pure (see module doc)
    ts_unique, t_rank = np.unique(t, return_inverse=True)
    t_rank = t_rank.astype(np.int32).reshape(t.shape)
    arrays = {
        "parents": parents,
        "creator": creator,
        "t_rank": t_rank,
        "coin": coin,
        "stake": packed.stake,
        "fork_pairs": packed.fork_pairs,
        "member_table": packed.member_table,
        "n_valid": np.int32(n),
    }
    statics = {
        "tot_stake": int(packed.stake.sum()),
        "coin_period": config.coin_period,
        "block": block,
        "r_max": r_max,
        "s_max": s_max,
        "chain": chain,
        "has_forks": bool(len(packed.fork_pairs)),
        "matmul_dtype_name": matmul_dtype_name,
    }
    return arrays, statics, ts_unique


def run_consensus(
    packed: PackedDAG,
    config: Optional[SwirldConfig] = None,
    *,
    block: int = 128,
    r_max: Optional[int] = None,
    s_max: Optional[int] = None,
    matmul_dtype_name: Optional[str] = None,
    mesh=None,
    use_pallas_ssm: bool = False,
    ssm_mode: Optional[str] = None,
) -> ConsensusResult:
    """Run the full pipeline on a packed DAG and extract the final order.

    The device computes everything except the tiebreak hash; the host
    applies the oracle's exact sort key (round received, consensus ts,
    BLAKE2b(whiten || id)) to produce the total order.  With ``mesh`` (a
    1-D member-axis ``jax.sharding.Mesh``), the strongly-sees phase is
    sharded over the mesh with psum stake aggregation
    (:mod:`tpu_swirld.parallel`).
    """
    arrays, statics, ts_unique = prepare_inputs(
        packed, config, block=block, r_max=r_max, s_max=s_max,
        matmul_dtype_name=matmul_dtype_name,
    )
    config = config or SwirldConfig(n_members=packed.n_members)
    n = packed.n
    o = obs.current()
    if o is not None:
        _record_shapes(
            o, n=n, n_pad=arrays["parents"].shape[0], statics=statics
        )
    parents, creator, t_rank, coin = (
        arrays["parents"], arrays["creator"], arrays["t_rank"], arrays["coin"]
    )
    member_table, stake = arrays["member_table"], arrays["stake"]
    r_max, s_max = statics["r_max"], statics["s_max"]
    chain = statics["chain"]
    tot = statics["tot_stake"]
    matmul_dtype_name = statics["matmul_dtype_name"]
    if ssm_mode not in (None, "columns", "full"):
        raise ValueError(f"unknown ssm_mode {ssm_mode!r}")
    if mesh is not None and use_pallas_ssm:
        raise NotImplementedError(
            "use_pallas_ssm is not yet routed through the sharded (mesh) "
            "path; run one or the other"
        )
    if ssm_mode == "columns" and (mesh is not None or use_pallas_ssm):
        raise NotImplementedError(
            "ssm_mode='columns' is not routed through the mesh/pallas "
            "paths yet; those run the full-matrix kernel"
        )
    if ssm_mode is None:
        # auto: column-restricted on the plain single-host path, full
        # matrix for the fused mesh / pallas kernels
        ssm_mode = "full" if (mesh is not None or use_pallas_ssm) else "columns"
    if mesh is not None:
        from tpu_swirld.parallel import consensus_fn_for_mesh, pad_members

        member_table, stake = pad_members(
            member_table, stake, mesh.devices.size
        )
        kernel = consensus_fn_for_mesh(mesh)
        if o is not None:
            o.registry.gauge("mesh_devices").set(int(mesh.devices.size))
        # max_round never exceeds the longest self-chain; bound the fused
        # kernel's witness table accordingly (same bound as the staged path)
        r_max = min(r_max, _bucket(chain + 1, 32))
        if o is not None:
            o.registry.gauge("pipeline_r_max").set(r_max)
        out = obs.stage_call(
            "pipeline.mesh_consensus",
            kernel,
            jnp.asarray(parents),
            jnp.asarray(creator),
            jnp.asarray(t_rank),
            jnp.asarray(coin),
            jnp.asarray(stake),
            jnp.asarray(packed.fork_pairs),
            jnp.asarray(member_table),
            jnp.asarray(n, dtype=jnp.int32),
            tot_stake=tot,
            coin_period=config.coin_period,
            block=block,
            r_max=r_max,
            s_max=s_max,
            chain=chain,
            has_forks=bool(len(packed.fork_pairs)),
            matmul_dtype_name=matmul_dtype_name,
        )
        t_dev0 = time.perf_counter()
        out = jax.tree.map(np.asarray, out)   # blocks on device completion
        t_device = time.perf_counter() - t_dev0
        if bool(out["overflow"]):
            raise RuntimeError(
                "witness table overflow: raise config.max_rounds / s_max"
            )
        t_fin0 = time.perf_counter()
        with _maybe_span(o, "pipeline.finalize"):
            result = finalize_order(packed, out, ts_unique)
        result.timings = {
            "device_and_dispatch": round(t_device, 6),
            "finalize_host": round(time.perf_counter() - t_fin0, 6),
        }
        return result

    # single-host path: two stages with a tight fame/order r_max.
    # max_round never exceeds the longest self-chain (a member's round
    # rises at most once per own event), so the witness table is bounded
    # by chain+1 rounds; bucket to limit recompiles.
    r_rounds = min(r_max, _bucket(chain + 1, 32))
    if o is not None:
        o.registry.gauge("pipeline_r_max").set(r_rounds)
    if ssm_mode == "columns" and not use_pallas_ssm:
        return _run_consensus_columns(
            packed, config, parents, creator, t_rank, coin, stake,
            member_table, ts_unique, n=n, tot=tot, block=block,
            r_rounds=r_rounds, s_max=s_max, chain=chain,
            matmul_dtype_name=matmul_dtype_name,
        )
    stage_a_fn = rounds_stage
    if use_pallas_ssm:
        stage_a_fn = rounds_stage_pallas(
            interpret=jax.default_backend() != "tpu"
        )
    t_dev0 = time.perf_counter()
    stage_a = obs.stage_call(
        "pipeline.rounds_stage",
        stage_a_fn,
        jnp.asarray(parents),
        jnp.asarray(creator),
        jnp.asarray(stake),
        jnp.asarray(packed.fork_pairs),
        jnp.asarray(member_table),
        jnp.asarray(n, dtype=jnp.int32),
        tot_stake=tot,
        block=block,
        r_max=r_rounds,
        s_max=s_max,
        has_forks=bool(len(packed.fork_pairs)),
        matmul_dtype_name=matmul_dtype_name,
    )
    if bool(stage_a["overflow"]):
        raise RuntimeError(
            "witness table overflow: raise config.max_rounds / s_max"
        )
    max_round = int(stage_a["max_round"])     # device -> host scalar
    r_tight = min(r_rounds, _bucket(max_round + 3, 8))
    stage_b = obs.stage_call(
        "pipeline.fame_order_stage",
        fame_order_stage,
        stage_a["anc"],
        stage_a["sees"],
        stage_a["ssm"],
        stage_a["wit_table"],
        stage_a["wit_count"],
        jnp.asarray(creator),
        jnp.asarray(coin),
        jnp.asarray(stake),
        jnp.asarray(parents[:, 0]),
        jnp.asarray(t_rank),
        stage_a["max_round"],
        jnp.asarray(n, dtype=jnp.int32),
        tot_stake=tot,
        coin_period=config.coin_period,
        r_max=r_tight,
        s_max=s_max,
        chain=chain,
        has_forks=bool(len(packed.fork_pairs)),
        matmul_dtype_name=matmul_dtype_name,
    )
    out = {
        "round": stage_a["round"],
        "is_witness": stage_a["is_witness"],
        "wit_table": stage_a["wit_table"][:r_tight],
        "wit_count": stage_a["wit_count"][:r_tight],
        "max_round": stage_a["max_round"],
        **stage_b,
    }
    out = jax.tree.map(np.asarray, out)       # blocks on device completion
    t_device = time.perf_counter() - t_dev0
    t_fin0 = time.perf_counter()
    with _maybe_span(o, "pipeline.finalize"):
        result = finalize_order(packed, out, ts_unique)
    result.timings = {
        "device_and_dispatch": round(t_device, 6),
        "finalize_host": round(time.perf_counter() - t_fin0, 6),
    }
    return result


def _run_consensus_columns(
    packed, config, parents, creator, t_rank, coin, stake, member_table,
    ts_unique, *, n, tot, block, r_rounds, s_max, chain, matmul_dtype_name,
):
    """Column-restricted strongly-sees execution (the default path).

    Strongly-see columns are pure DAG functions (round-independent), and
    the rounds scan only queries *witness* columns, so instead of the full
    Θ(N³) matrix we compute columns only as witnesses are discovered: the
    scan runs in chunks carrying its state; when a chunk registers a
    witness that has no column yet, the column is computed and just that
    chunk re-runs (exact, because columns don't depend on rounds).  Every
    query in the final pass over each chunk was answered exactly, so the
    result is bit-identical to the full-matrix scan at Θ(N²·W) cost
    (W ≈ 10% of N in gossip DAGs).
    """
    n_pad = parents.shape[0]
    has_forks = bool(len(packed.fork_pairs))
    o = obs.current()
    t_dev0 = time.perf_counter()
    parents_d = jnp.asarray(parents)
    creator_d = jnp.asarray(creator)
    stake_d = jnp.asarray(stake)
    mt_d = jnp.asarray(member_table)
    n_d = jnp.asarray(n, dtype=jnp.int32)
    anc, sees = obs.stage_call(
        "pipeline.visibility_stage",
        visibility_stage,
        parents_d, creator_d, jnp.asarray(packed.fork_pairs),
        n_members=int(stake.shape[0]), block=block,
        matmul_dtype_name=matmul_dtype_name,
    )
    a3, b3 = obs.stage_call("pipeline.member_slabs", member_slabs, sees, mt_d)

    # incremental column store: a preallocated (N, W_CAP) buffer written
    # in place so the scan's input shape stays stable (W_CAP grows in
    # 1024-buckets only); positions tracked host-side.  Every column is
    # exact regardless of round state.
    col_pos = np.full((n_pad,), -1, dtype=np.int32)
    n_cols = 0
    w_cap = min(_bucket(max(s_max * 8, 256), 256), n_pad)
    ssm_c = jnp.zeros((n_pad, w_cap), dtype=bool)
    n_scans = 0

    def add_columns(events):
        nonlocal n_cols, ssm_c, w_cap
        # bucket only the matmul batch and the buffer CAPACITY; occupancy
        # advances by the real count so padding slots are reused
        batch = _bucket(len(events), 16)
        if n_cols + batch > w_cap:
            w_cap = _bucket(
                max(n_cols + batch, min(w_cap * 2, n_pad)), 256
            )
            ssm_c = jnp.pad(ssm_c, ((0, 0), (0, w_cap - ssm_c.shape[1])))
        cols_arr = np.full((batch,), -1, dtype=np.int32)
        cols_arr[: len(events)] = events
        part = obs.stage_call(
            "pipeline.ssm_cols_stage",
            ssm_cols_stage,
            a3, b3, stake_d, jnp.asarray(cols_arr), tot_stake=tot,
            matmul_dtype_name=matmul_dtype_name,
        )
        for j, e in enumerate(events):
            col_pos[e] = n_cols + j
        ssm_c = lax.dynamic_update_slice(ssm_c, part, (0, n_cols))
        n_cols += len(events)

    add_columns([int(i) for i in np.where(packed.parents[:, 0] < 0)[0]])

    # chunked scan: resume from the carried state; when a chunk registers
    # a witness whose column is missing AND a later event in the chunk
    # queried that witness's round, compute the column and re-run just
    # that chunk (columns are round-independent, so the re-run is exact);
    # otherwise the chunk's outputs are already exact and the new columns
    # only serve future chunks.
    chunk_size = min(128, n_pad)
    while n_pad % chunk_size:
        chunk_size //= 2
    state = (
        jnp.zeros((n_pad,), dtype=jnp.int32),
        jnp.zeros((n_pad,), dtype=bool),
        jnp.full((r_rounds, s_max), -1, dtype=jnp.int32),
        jnp.zeros((r_rounds,), dtype=jnp.int32),
        jnp.zeros((), dtype=bool),
    )
    parents_np = parents
    for start in range(0, n_pad, chunk_size):
        start_d = jnp.asarray(start, dtype=jnp.int32)
        # each failed attempt adds at least one column, and a chunk can
        # register at most chunk_size witnesses, so this bound is safe
        # even for degenerate one-round-per-event DAGs (2-member gossip)
        for _attempt in range(chunk_size + 1):
            out = obs.stage_call(
                "pipeline.rounds_chunk_stage",
                rounds_chunk_stage,
                parents_d, ssm_c, jnp.asarray(col_pos), creator_d,
                stake_d, n_d, *state, start_d,
                tot_stake=tot, r_max=r_rounds, s_max=s_max,
                has_forks=has_forks, chunk=chunk_size,
            )
            n_scans += 1
            tab = np.asarray(out[2])
            registered = np.unique(tab[tab >= 0])
            missing = registered[col_pos[registered] < 0]
            if missing.size == 0:
                state = out
                break
            rnd_np = np.asarray(out[0])
            # was any missing witness's round queried later in this chunk?
            ce = np.arange(start, start + chunk_size)
            p = parents_np[ce]
            r0 = np.where(
                p[:, 0] < 0,
                -1,
                np.maximum(rnd_np[np.maximum(p[:, 0], 0)],
                           rnd_np[np.maximum(p[:, 1], 0)]),
            )
            affected = False
            for w in missing:
                if w < start:       # registered in an earlier chunk state?
                    affected = True  # (shouldn't happen; be safe)
                    break
                later = ce > w
                if np.any(later & (r0 == rnd_np[w])):
                    affected = True
                    break
            add_columns([int(e) for e in missing])
            if not affected:
                state = out
                break
        else:
            raise RuntimeError("witness-column chunk did not converge")
    rnd_a, wits_a, tab_a, cnt_a, overflow_a = state
    if bool(overflow_a):
        raise RuntimeError(
            "witness table overflow: raise config.max_rounds / s_max"
        )
    max_round_d = jnp.max(jnp.where(jnp.arange(n_pad) < n_d, rnd_a, 0))
    max_round = int(max_round_d)
    r_tight = min(r_rounds, _bucket(max_round + 3, 8))
    stage_b = obs.stage_call(
        "pipeline.fame_order_cols_stage",
        fame_order_cols_stage,
        anc, sees, ssm_c, jnp.asarray(col_pos), tab_a, cnt_a,
        creator_d, jnp.asarray(coin), stake_d,
        jnp.asarray(parents[:, 0]), jnp.asarray(t_rank),
        max_round_d, n_d,
        tot_stake=tot, coin_period=config.coin_period, r_max=r_tight,
        s_max=s_max, chain=chain, has_forks=has_forks,
        matmul_dtype_name=matmul_dtype_name,
    )
    out = {
        "round": rnd_a,
        "is_witness": wits_a,
        "wit_table": tab_a[:r_tight],
        "wit_count": cnt_a[:r_tight],
        "max_round": max_round_d,
        **stage_b,
    }
    out = jax.tree.map(np.asarray, out)
    t_device = time.perf_counter() - t_dev0
    t_fin0 = time.perf_counter()
    with _maybe_span(o, "pipeline.finalize"):
        result = finalize_order(packed, out, ts_unique)
    if o is not None:
        o.registry.counter("pipeline_ssm_columns_total").inc(n_cols)
        o.registry.counter("pipeline_chunk_scans_total").inc(n_scans)
    result.timings = {
        "device_and_dispatch": round(t_device, 6),
        "finalize_host": round(time.perf_counter() - t_fin0, 6),
        "ssm_columns": n_cols,
        "ssm_col_iterations": n_scans,
    }
    return result


def finalize_order(
    packed: PackedDAG, out: Dict[str, np.ndarray], ts_unique: np.ndarray
) -> ConsensusResult:
    """Host post-pass: fame dict, whitened tiebreak, final total order."""
    n = packed.n
    tab = out["wit_table"]
    famous_grid = out["famous"].reshape(tab.shape)
    famous: Dict[int, Optional[bool]] = {}
    r_max, s_max = tab.shape
    ufw_by_round: Dict[int, List[int]] = {}
    for r in range(r_max):
        fam_slots = []
        for s in range(s_max):
            e = int(tab[r, s])
            if e < 0:
                continue
            f = int(famous_grid[r, s])
            famous[e] = None if f < 0 else bool(f)
            if f == 1:
                fam_slots.append(e)
        if fam_slots:
            by_creator: Dict[int, List[int]] = {}
            for e in fam_slots:
                by_creator.setdefault(int(packed.creator[e]), []).append(e)
            ufw_by_round[r] = sorted(
                e for v in by_creator.values() if len(v) == 1 for e in v
            )

    rr = out["round_received"][:n]
    # map timestamp ranks back to the int64 values
    rank = np.clip(out["consensus_ts_rank"][:n], 0, len(ts_unique) - 1)
    cts = np.where(rr >= 0, ts_unique[rank], 0).astype(np.int64)
    whiten_cache: Dict[int, bytes] = {}

    def whiten(r: int) -> bytes:
        w = whiten_cache.get(r)
        if w is None:
            w = bytes(crypto.SIG_BYTES)
            for e in ufw_by_round.get(r, []):
                w = xor_bytes(w, packed.sigs[e])
            whiten_cache[r] = w
        return w

    received = [
        (int(rr[i]), int(cts[i]), crypto.hash_bytes(whiten(int(rr[i])) + packed.ids[i]), i)
        for i in range(n)
        if rr[i] >= 0
    ]
    received.sort(key=lambda item: (item[0], item[1], item[2]))
    return ConsensusResult(
        n=n,
        round=out["round"][:n],
        is_witness=out["is_witness"][:n],
        famous=famous,
        round_received=rr,
        consensus_ts=cts,
        order=[i for (_r, _t, _h, i) in received],
        max_round=int(out["max_round"]),
    )
