"""Batched JAX/XLA consensus pipeline (the TPU execution backend)."""

from tpu_swirld.tpu.pipeline import ConsensusResult, consensus_arrays, run_consensus

__all__ = ["ConsensusResult", "consensus_arrays", "run_consensus"]
