"""Pallas TPU kernels for the consensus pipeline's hot op.

The strongly-sees matrix is the pipeline's FLOP bottleneck (Θ(N²·N/M·M)
boolean-matmul work) and the kernel BASELINE.json's north star names
("batched boolean matrix-power / BFS-style reachability kernel in
Pallas").  The XLA path (:func:`tpu_swirld.tpu.pipeline.ssm_matrix`)
re-gathers the per-member slabs and materializes an N×N int32 tally in
HBM on every member iteration; this kernel instead

- pre-gathers the member slabs ONCE into two dense operands with affine
  block indexing:  ``A[N, M*K]`` ("x sees z", creator-grouped columns) and
  ``B[M*K, N]`` ("z sees w"),
- walks a ``(N/Tm, N/Tn, M)`` grid with the member axis innermost; the
  per-tile stake tally lives in a VMEM scratch accumulator across the
  member steps (TPU grids execute sequentially, so the scratch persists),
- performs each member's ``(Tm,K)@(K,Tn)`` hop on the MXU in bfloat16
  (0/1 products, f32 accumulation — exact), thresholds >0 into the
  int32 stake tally on the VPU, and
- writes the strict-2/3 supermajority bool tile exactly once, on the
  last member step.

HBM traffic: A is read N/Tn times, B N/Tm times, the output written once
— the int32 tally never touches HBM (the XLA path rewrites it M times).

Beyond the full-matrix kernel, this module carries the **window-extension
tile kernels** of the streaming/incremental drivers
(:func:`make_extension_kernels`): :func:`ssm_block_pallas` (strongly-sees
rows-×-columns blocks gathered straight from the resident sees slab — the
``ssm_block_fn`` seam) and :func:`bmm_or_pallas` (the blockwise ancestry
extension's boolean-matmul hop).  All kernels run bit-identically in
interpret mode, which is how CPU runs and the parity tests exercise them.

Correctness is pinned against the XLA stages by interpret-mode parity
tests (``tests/test_pallas.py``), including ragged edge shapes (windows
not tile-aligned, single-event chunks, post-widen shapes); real-TPU
timing is pending hardware availability (the axon tunnel did not
initialize this round).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


@functools.lru_cache(maxsize=None)
def accel_compiled() -> bool:
    """True when the default backend lowers Pallas kernels natively
    (TPU via Mosaic, GPU via Triton).  CPU has no native lowering and
    runs interpret mode — bit-identical, per the parity pin of
    ``tests/test_pallas.py``."""
    return jax.default_backend() in ("tpu", "gpu")


def resolve_interpret(interpret: Optional[bool] = None) -> bool:
    """The capability probe behind every kernel factory's
    ``interpret=None`` default: an explicit True/False wins; None
    compiles on TPU/GPU and falls back to interpret mode on CPU, so the
    same driver construction runs the compiled kernels wherever the
    hardware can and stays exact everywhere else."""
    if interpret is None:
        return not accel_compiled()
    return bool(interpret)

# renamed TPUCompilerParams -> CompilerParams across JAX releases
_CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)


def _compiler_params(**kw):
    if _CompilerParams is None:
        raise RuntimeError(
            "incompatible JAX: jax.experimental.pallas.tpu exposes neither "
            "CompilerParams nor TPUCompilerParams"
        )
    return _CompilerParams(**kw)


def _ssm_kernel(stake_ref, a_ref, b_ref, out_ref, acc_ref, *, n_members,
                tot_stake):
    m = pl.program_id(2)

    @pl.when(m == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    hit = (
        jnp.dot(a_ref[:], b_ref[:], preferred_element_type=jnp.float32)
        > 0.5
    )
    acc_ref[:] += hit.astype(jnp.int32) * stake_ref[m]

    @pl.when(m == n_members - 1)
    def _():
        out_ref[:] = 3 * acc_ref[:] > 2 * tot_stake


def ssm_matrix_pallas(
    sees: jnp.ndarray,
    member_table: jnp.ndarray,
    stake: jnp.ndarray,
    tot_stake: int,
    matmul_dtype=jnp.bfloat16,
    *,
    tile_m: int = 256,
    tile_n: int = 256,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Strongly-sees (∃-z rule) as a single Pallas kernel.  Drop-in
    replacement for :func:`tpu_swirld.tpu.pipeline.ssm_matrix` (pass via
    ``run_consensus(..., use_pallas_ssm=True)``).  ``interpret=None``
    resolves via :func:`resolve_interpret` (compiled on TPU/GPU)."""
    interpret = resolve_interpret(interpret)
    n = sees.shape[0]
    n_members, k = member_table.shape
    tile_m = _fit_tile(tile_m, n)
    tile_n = _fit_tile(tile_n, n)
    k_pad = max(128, ((k + 127) // 128) * 128)

    idx = member_table.reshape(-1)
    valid = idx >= 0
    idxc = jnp.clip(idx, 0, n - 1)
    # creator-grouped slabs, padded to (M, k_pad) columns/rows
    a = (sees[:, idxc] & valid[None, :]).astype(matmul_dtype)      # N, M*k
    b = (sees[idxc, :] & valid[:, None]).astype(matmul_dtype)      # M*k, N
    if k_pad != k:
        a = jnp.pad(
            a.reshape(n, n_members, k), ((0, 0), (0, 0), (0, k_pad - k))
        ).reshape(n, n_members * k_pad)
        b = jnp.pad(
            b.reshape(n_members, k, n), ((0, 0), (0, k_pad - k), (0, 0))
        ).reshape(n_members * k_pad, n)

    kernel = functools.partial(
        _ssm_kernel, n_members=n_members, tot_stake=tot_stake
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, n), jnp.bool_),
        grid=(n // tile_m, n // tile_n, n_members),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),          # stake, whole
            pl.BlockSpec(
                (tile_m, k_pad),
                lambda i, j, m: (i, m),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (k_pad, tile_n),
                lambda i, j, m: (m, j),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (tile_m, tile_n),
            lambda i, j, m: (i, j),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[pltpu.VMEM((tile_m, tile_n), jnp.int32)],
        interpret=interpret,
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(stake.astype(jnp.int32), a, b)


def make_ssm_fn(*, interpret: Optional[bool] = None, tile_m: int = 256,
                tile_n: int = 256):
    """Adapter matching the ``ssm_fn`` seam of ``rounds_body``."""
    interpret = resolve_interpret(interpret)

    def ssm_fn(sees, member_table, stake, tot_stake, dtype):
        return ssm_matrix_pallas(
            sees, member_table, stake, tot_stake, dtype,
            tile_m=tile_m, tile_n=tile_n, interpret=interpret,
        )

    return ssm_fn


def _fit_tile(t: int, n: int) -> int:
    """Shrink the requested tile by halving until it divides ``n`` (all
    pipeline shapes are power-of-two-friendly buckets; a non-dividing
    odd ``n`` is rejected rather than searched for exotic divisors)."""
    t = min(t, n)
    while n % t:
        t //= 2
    if t < 8:
        raise ValueError(f"no usable tile for n={n}")
    return t


@functools.partial(
    jax.jit,
    static_argnames=("rows", "tot_stake", "matmul_dtype_name", "tile_m",
                     "tile_n", "interpret"),
)
def ssm_block_pallas(sees, member_table, stake, cols, row0, *, rows,
                     tot_stake, matmul_dtype_name,
                     tile_m: int = 256, tile_n: int = 128,
                     interpret: Optional[bool] = None):
    """Strongly-sees *block* for window rows ``[row0, row0 + rows)`` ×
    column events ``cols`` as one Pallas kernel — the windowed
    counterpart of :func:`ssm_matrix_pallas`, matching the
    ``ssm_block_fn`` seam of :func:`tpu_swirld.tpu.pipeline.
    ssm_block_stage`.

    The row/column gathers read **tiles of the sees slab directly** (the
    one slab the store budgets — no resident per-member gather slabs); the
    kernel then walks a ``(rows/Tm, C/Tn, M)`` grid with the member axis
    innermost, accumulating the per-tile stake tally in VMEM scratch
    exactly as the full-matrix kernel does — the int32 tally never
    touches HBM.
    """
    interpret = resolve_interpret(interpret)   # static: resolved at trace
    matmul_dtype = (
        jnp.bfloat16 if matmul_dtype_name == "bfloat16" else jnp.float32
    )
    n = sees.shape[0]
    n_members, k = member_table.shape
    c = cols.shape[0]
    tile_m = _fit_tile(tile_m, rows)
    tile_n = _fit_tile(tile_n, c)
    k_pad = max(128, ((k + 127) // 128) * 128)
    idx = member_table.reshape(-1)
    valid = idx >= 0
    idxc = jnp.clip(idx, 0, n - 1)
    colsc = jnp.clip(cols, 0, n - 1)
    col_valid = cols >= 0
    sees_rows = jax.lax.dynamic_slice(sees, (row0, 0), (rows, n))
    a = (
        (sees_rows[:, idxc] & valid[None, :])
        .reshape(rows, n_members, k)
    )                                                           # rows, M, K
    b_cols = (
        sees[idxc[:, None], colsc[None, :]]
        & valid[:, None] & col_valid[None, :]
    ).reshape(n_members, k, c)                                  # M, K, C
    if k_pad != k:
        a = jnp.pad(a, ((0, 0), (0, 0), (0, k_pad - k)))
        b_cols = jnp.pad(b_cols, ((0, 0), (0, k_pad - k), (0, 0)))
    a = a.reshape(rows, n_members * k_pad).astype(matmul_dtype)
    b_cols = b_cols.reshape(n_members * k_pad, c).astype(matmul_dtype)

    kernel = functools.partial(
        _ssm_kernel, n_members=n_members, tot_stake=tot_stake
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, c), jnp.bool_),
        grid=(rows // tile_m, c // tile_n, n_members),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),              # stake
            pl.BlockSpec(
                (tile_m, k_pad),
                lambda i, j, m: (i, m),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (k_pad, tile_n),
                lambda i, j, m: (m, j),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (tile_m, tile_n),
            lambda i, j, m: (i, j),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[pltpu.VMEM((tile_m, tile_n), jnp.int32)],
        interpret=interpret,
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(stake.astype(jnp.int32), a, b_cols)
    return out & col_valid[None, :]


def make_ssm_block_fn(*, interpret: Optional[bool] = None,
                      tile_m: int = 256, tile_n: int = 128):
    """Adapter matching the ``ssm_block_fn`` seam of the incremental /
    streaming drivers (:class:`tpu_swirld.tpu.pipeline.
    IncrementalConsensus`) and of :func:`tpu_swirld.tpu.pipeline.
    _columns_pass`."""
    interpret = resolve_interpret(interpret)

    def ssm_block_fn(sees, member_table, stake, cols, row0, *, rows,
                     tot_stake, matmul_dtype_name):
        return ssm_block_pallas(
            sees, member_table, stake, cols, row0, rows=rows,
            tot_stake=tot_stake, matmul_dtype_name=matmul_dtype_name,
            tile_m=tile_m, tile_n=tile_n, interpret=interpret,
        )

    return ssm_block_fn


def _bmm_kernel(a_ref, b_ref, out_ref):
    out_ref[:] = (
        jnp.dot(a_ref[:], b_ref[:], preferred_element_type=jnp.float32)
        > 0.5
    )


def bmm_or_pallas(a, b, matmul_dtype, *, tile_m: int = 128,
                  tile_n: int = 256, interpret: Optional[bool] = None):
    """Tiled boolean matmul (OR over 0/1 products) as a Pallas kernel —
    the MXU hop of the blockwise ancestry extension (``ExtensionKernels.
    bmm``).  The contraction axis (one event block) rides whole into
    VMEM; the output grid is ``(P/Tm, R/Tn)``.  Exact: 0/1 products with
    f32 accumulation, thresholded at 0.5."""
    interpret = resolve_interpret(interpret)
    p, q = a.shape
    r = b.shape[1]
    try:
        tile_m = _fit_tile(tile_m, p)
        tile_n = _fit_tile(tile_n, r)
    except ValueError:
        # shapes the grid cannot tile — e.g. the forked fused stage's
        # n_members-wide one-hot hop on a small network — take the plain
        # XLA matmul (exact either way; only the hot shapes need the MXU)
        return (
            jnp.matmul(
                a.astype(matmul_dtype), b.astype(matmul_dtype),
                preferred_element_type=jnp.float32,
            )
            > 0.5
        )
    q_pad = max(128, ((q + 127) // 128) * 128)
    am = a.astype(matmul_dtype)
    bm = b.astype(matmul_dtype)
    if q_pad != q:
        am = jnp.pad(am, ((0, 0), (0, q_pad - q)))
        bm = jnp.pad(bm, ((0, q_pad - q), (0, 0)))
    return pl.pallas_call(
        _bmm_kernel,
        out_shape=jax.ShapeDtypeStruct((p, r), jnp.bool_),
        grid=(p // tile_m, r // tile_n),
        in_specs=[
            pl.BlockSpec(
                (tile_m, q_pad), lambda i, j: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (q_pad, tile_n), lambda i, j: (0, j),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (tile_m, tile_n), lambda i, j: (i, j),
            memory_space=pltpu.VMEM,
        ),
        interpret=interpret,
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel"),
        ),
    )(am, bm)


def make_mesh_row_block_fn(mesh, *, interpret: Optional[bool] = None):
    """The row-sharded streaming block kernel
    (:func:`tpu_swirld.parallel.make_row_sharded_block_fn`) with
    :func:`bmm_or_pallas` as the shard-local matmul hop: the halo
    exchange and stake-tally psum stay XLA collectives, while each
    device's ``(rows, K) @ (K, C)`` member hops ride the MXU tile
    kernel.  Exact for the same reason the single-device pairing is
    (0/1 products, f32 accumulation, shared threshold)."""
    from tpu_swirld.parallel import make_row_sharded_block_fn

    interpret = resolve_interpret(interpret)

    def bmm(a, b, dtype):
        return bmm_or_pallas(a, b, dtype, interpret=interpret)

    return make_row_sharded_block_fn(mesh, bmm=bmm)


def make_extension_kernels(*, interpret: Optional[bool] = None,
                           tile_m: int = 256, tile_n: int = 128):
    """The Pallas :class:`~tpu_swirld.tpu.pipeline.ExtensionKernels`
    bundle for the window-extension hot path: the blockwise ancestry
    boolean-matmul hop and the strongly-sees block kernel, both consuming
    sees/ancestry slab tiles directly.  ``interpret=True`` runs the same
    kernels bit-identically off-TPU (the parity pin of
    ``tests/test_pallas.py``)."""
    from tpu_swirld.tpu.pipeline import ExtensionKernels

    interpret = resolve_interpret(interpret)

    def bmm(a, b, dtype):
        return bmm_or_pallas(a, b, dtype, interpret=interpret)

    return ExtensionKernels(
        name=f"pallas{'-interpret' if interpret else ''}",
        bmm=bmm,
        ssm_block_fn=make_ssm_block_fn(
            interpret=interpret, tile_m=tile_m, tile_n=tile_n
        ),
    )
