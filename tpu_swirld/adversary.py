"""Active byzantine adversary library + scenario registry.

The chaos harness (:mod:`tpu_swirld.chaos`) exercises crash/omission
faults — lossy links, partitions, restarts — but the whitepaper's
guarantees are stated against *active* adversaries: members that fork,
censor, and strategically time their releases, up to the ``n > 3f``
budget.  This module supplies that adversary class as malicious node
drivers riding the existing :class:`~tpu_swirld.transport.Transport`
seam (so byzantine behavior composes with injected network faults), plus
a registry of named scenarios with machine-checked verdicts:

- :class:`EquivocationStorm` — an equivocating member maintaining
  ``n_branches`` live branch views of its own chain (the 2-branch
  :class:`~tpu_swirld.sim.DivergentForker` generalized), minting fork
  pairs at a configurable rate inside a timed attack window and serving
  different branches to different peers.
- :class:`CensorshipRelay` — a relay that answers syncs honestly EXCEPT
  it drops a chosen victim's events from every reply (sync and
  want-list) during the attack window.  The victim's events still reach
  peers through other routes; the relay's selective silence is what the
  honest side's withholding heuristic must flag.
- :class:`DelayedReleaseStraggler` — withholds its OWN events from every
  reply during a hold window while continuing to pull gossip and extend
  its chain, then releases the whole tail at once.  This is
  :func:`~tpu_swirld.sim.make_straggler_event` generalized into a timed
  strategy: held long enough, the released witnesses land below the
  honest nodes' frozen vote horizon and must register as
  ``late_witnesses`` with zero ``horizon_violations``.
- **fork bomb** — coordinated :class:`EquivocationStorm` drivers at
  ``f = (n-1)//3`` creators (must survive: safety + liveness + zero
  budget flags) and at ``f+1`` (must be *flagged* via the nodes'
  ``budget_exhausted`` admission check, never a silent divergence).

Every scenario runs as a :class:`~tpu_swirld.chaos.ChaosScenario` (the
drivers install through ``ChaosScenario.adversaries``) and produces the
standard chaos verdict — honest decided prefixes bit-identical to the
fault-free oracle replay, decided index advancing after the attack
window — extended with a cross-engine parity section (oracle batch
replay + the chosen windowed driver) and an ``adversary`` section with
the detection counters (``equivocations_detected``,
``withholding_suspected``, ``budget_exhausted``).

``SCENARIOS`` maps scenario name -> runner with the uniform signature
``runner(ckpt_dir, seed=None, engine="incremental", metrics=None,
tracer=None)``; ``scripts/chaos_run.py`` builds its CLI from this
registry, so a newly registered strategy auto-appears in ``--scenario``
and ``--all``.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from tpu_swirld import crypto
from tpu_swirld.chaos import ChaosScenario, ChaosSimulation, _engines_agree
from tpu_swirld.oracle.event import Event, decode_event, encode_event
from tpu_swirld.oracle.node import Node


def _decode_blob(reply: bytes):
    """Split one of OUR OWN signed reply blobs back into events (the
    driver re-filters and re-signs with its own key, so no verification
    is needed here — the inner node just produced the blob)."""
    blob = reply[: -crypto.SIG_BYTES]
    events = []
    off = 0
    while off < len(blob):
        ev, off = decode_event(blob, off)
        events.append(ev)
    return events


def _sign_blob(events, sk: bytes) -> bytes:
    blob = b"".join(encode_event(ev) for ev in events)
    return blob + crypto.sign(blob, sk, crypto.DOMAIN_SYNC_REPLY)


class _InnerNodeDriver:
    """Shared plumbing: an adversary that fronts one honest inner
    :class:`Node` (same member key) and rewrites its replies."""

    def __init__(self, sim: ChaosSimulation, index: int):
        pk, sk = sim.keys[index]
        self.pk, self.sk = pk, sk
        self.clock = sim.clock           # [turn] — shared logical time
        self.rng = sim.rng
        self.node = Node(
            sk=sk, pk=pk, network=sim.network, members=sim.members,
            config=sim.config, clock=lambda: self.clock[0],
            network_want=sim.network_want, transport=sim.transport,
        )

    def _gossip(self, honest_pks: List[bytes]) -> None:
        """Keep the inner node a live participant: pull one honest peer
        and extend the self-chain (no consensus pass — serving replies
        only needs the store)."""
        peer = honest_pks[self.rng.randrange(len(honest_pks))]
        try:
            self.node.sync(peer, b"adv:%d" % len(self.node.hg))
        except ValueError:
            pass

    # default endpoints: honest passthrough (subclasses filter)
    def ask_sync(self, from_pk: bytes, req: bytes) -> bytes:
        return self.node.ask_sync(from_pk, req)

    def ask_events(self, from_pk: bytes, req: bytes) -> bytes:
        return self.node.ask_events(from_pk, req)


class EquivocationStorm:
    """``n_branches``-way equivocator minting fork pairs at a set rate.

    Each branch is a full honest :class:`Node` sharing the forker's key
    (all branches create the identical deterministic genesis); peers are
    pinned to a branch round-robin on first contact, so different peers
    see different self-chains.  Inside the attack window every
    ``fork_every`` turns each branch pulls real gossip and extends its
    own chain — one fresh fork pair per branch pair per step.  Outside
    the window the storm goes quiet (it still serves its branches; an
    equivocation cannot be un-published).
    """

    def __init__(
        self,
        sim: ChaosSimulation,
        index: int,
        n_branches: int = 2,
        fork_every: int = 1,
        start: int = 0,
        end: Optional[int] = None,
    ):
        pk, sk = sim.keys[index]
        self.pk, self.sk = pk, sk
        self.clock = sim.clock
        self.rng = sim.rng
        self.fork_every = max(1, fork_every)
        self.start = start
        self.end = end
        self.branches = [
            Node(
                sk=sk, pk=pk, network=sim.network, members=sim.members,
                config=sim.config, clock=lambda: self.clock[0],
                network_want=sim.network_want, transport=sim.transport,
            )
            for _ in range(max(2, n_branches))
        ]
        self._heads = [br.head for br in self.branches]
        self._route: Dict[bytes, int] = {}

    def _branch_for(self, peer_pk: bytes) -> Node:
        b = self._route.get(peer_pk)
        if b is None:
            b = len(self._route) % len(self.branches)
            self._route[peer_pk] = b
        return self.branches[b]

    def ask_sync(self, from_pk: bytes, req: bytes) -> bytes:
        return self._branch_for(from_pk).ask_sync(from_pk, req)

    def ask_events(self, from_pk: bytes, req: bytes) -> bytes:
        return self._branch_for(from_pk).ask_events(from_pk, req)

    def step(self, turn: int, honest_pks: List[bytes]) -> None:
        if turn < self.start or (self.end is not None and turn >= self.end):
            return
        if (turn - self.start) % self.fork_every:
            return
        for bi, br in enumerate(self.branches):
            peer = honest_pks[self.rng.randrange(len(honest_pks))]
            try:
                br.pull(peer)
            except ValueError:
                pass
            op = br.member_events[peer][-1] if br.member_events[peer] else None
            if op is None:
                continue
            ev = Event(
                d=b"storm:%d:%d" % (bi, len(br.hg)),
                p=(self._heads[bi], op),
                t=br._now(),
                c=self.pk,
            ).signed(self.sk)
            br.add_event(ev)
            self._heads[bi] = ev.id


class CensorshipRelay(_InnerNodeDriver):
    """Selective withholding: answer every sync honestly, minus the
    victim's events.  Children of censored events still ship, so they
    orphan on the asker; its want-list round-trips come back to us and
    we censor those too — exactly the evidence pattern the honest side's
    ``withholding_suspected`` heuristic convicts on (the child we served
    proves we held the parent we refused)."""

    def __init__(
        self,
        sim: ChaosSimulation,
        index: int,
        victim_index: int,
        start: int = 0,
        end: Optional[int] = None,
    ):
        super().__init__(sim, index)
        self.victim_pk = sim.members[victim_index]
        self.start = start
        self.end = end

    def _censoring(self) -> bool:
        t = self.clock[0]
        return t >= self.start and (self.end is None or t < self.end)

    def _filter(self, reply: bytes) -> bytes:
        kept = [ev for ev in _decode_blob(reply) if ev.c != self.victim_pk]
        return _sign_blob(kept, self.sk)

    def ask_sync(self, from_pk: bytes, req: bytes) -> bytes:
        reply = self.node.ask_sync(from_pk, req)
        return self._filter(reply) if self._censoring() else reply

    def ask_events(self, from_pk: bytes, req: bytes) -> bytes:
        reply = self.node.ask_events(from_pk, req)
        return self._filter(reply) if self._censoring() else reply

    def step(self, turn: int, honest_pks: List[bytes]) -> None:
        self._gossip(honest_pks)


class DelayedReleaseStraggler(_InnerNodeDriver):
    """Timed self-withholding: keep pulling gossip and extending the own
    chain, but serve NONE of the events created inside the hold window —
    then release the whole tail at once.  Held past the honest frozen
    vote horizon, the released witnesses land below the committed
    frontier and must register as ``late_witnesses`` (full DAG citizens,
    decided not-famous by the ordinary vote structure) with zero
    ``horizon_violations`` — the timed generalization of the one-shot
    forged :func:`~tpu_swirld.sim.make_straggler_event`."""

    def __init__(
        self,
        sim: ChaosSimulation,
        index: int,
        hold_from: int = 0,
        release_at: int = 0,
    ):
        super().__init__(sim, index)
        self.hold_from = hold_from
        self.release_at = release_at
        self._visible: Optional[set] = None   # own ids servable while holding

    def _holding(self) -> bool:
        return self._visible is not None

    def _filter_own(self, reply: bytes) -> bytes:
        kept = [
            ev for ev in _decode_blob(reply)
            if ev.c != self.pk or ev.id in self._visible
        ]
        return _sign_blob(kept, self.sk)

    def ask_sync(self, from_pk: bytes, req: bytes) -> bytes:
        reply = self.node.ask_sync(from_pk, req)
        return self._filter_own(reply) if self._holding() else reply

    def ask_events(self, from_pk: bytes, req: bytes) -> bytes:
        reply = self.node.ask_events(from_pk, req)
        return self._filter_own(reply) if self._holding() else reply

    def step(self, turn: int, honest_pks: List[bytes]) -> None:
        if turn == self.hold_from:
            self._visible = set(self.node.member_events[self.pk])
        if turn >= self.release_at:
            self._visible = None
        self._gossip(honest_pks)


# ------------------------------------------------------------- verdicts


def _honest_counters(sim: ChaosSimulation) -> Dict:
    nodes = sim._live_honest()
    return {
        "equivocations_detected": max(
            (n.equivocations_detected for n in nodes), default=0
        ),
        "withholding_suspected": sum(n.withholding_suspected for n in nodes),
        "budget_exhausted": max((n.budget_exhausted for n in nodes), default=0),
        "sync_branches_capped": sum(n.sync_branches_capped for n in nodes),
        "late_witnesses": sum(len(n.late_witnesses) for n in nodes),
        "horizon_violations": sum(n.horizon_violations for n in nodes),
    }


def _with_engines(sim: ChaosSimulation, verdict: Dict, engine) -> Dict:
    """Fold the cross-engine parity section into the verdict: the most
    complete honest node's DAG replayed through the oracle batch pipeline
    AND the chosen windowed driver(s) must be bit-identical to its live
    state (``batch_oracle_parity`` covers the batch engine,
    ``incremental_batch_parity`` the windowed one).  ``engine`` is one
    driver name or a tuple of them — a tuple replays the same post-attack
    DAG through every named driver off one simulation run, which is how
    the test suite gets all-three-engine verdicts per strategy."""
    probe = max(sim._live_honest(), key=lambda n: len(n.hg))
    names = (engine,) if isinstance(engine, str) else tuple(engine)
    rows = [_engines_agree(probe, engine=e) for e in names]
    verdict["engines"] = rows[0] if len(rows) == 1 else rows
    verdict["ok"] = bool(
        verdict["ok"]
        and all(
            r["batch_oracle_parity"] and r["incremental_batch_parity"]
            for r in rows
        )
    )
    # the parity fold can flip a green run() verdict red: a red verdict
    # must still carry its flight-recorder bundle
    if not verdict["ok"] and not verdict.get("flightrec_dump"):
        verdict["flightrec_dump"] = sim.flightrec_postmortem(verdict)
    return verdict


# ------------------------------------------------------ scenario registry

#: scenario name -> runner(ckpt_dir, seed=None, engine=..., metrics=None,
#: tracer=None) -> verdict dict.  Insertion order is the display order.
SCENARIOS: Dict[str, Callable] = {}


def register_scenario(name: str):
    def deco(fn: Callable) -> Callable:
        SCENARIOS[name] = fn
        return fn
    return deco


@register_scenario("equivocation_storm")
def run_equivocation_storm(
    ckpt_dir: str, seed: Optional[int] = None, engine: str = "incremental",
    metrics=None, tracer=None, flightrec=None,
) -> Dict:
    """One storm forker (within the f=(n-1)//3 budget for n=5) minting
    fork pairs every other turn through a 110-turn window.  Verdict:
    safety + post-attack liveness + the fork detected
    (``equivocations_detected > 0``), never a budget flag."""
    seed = 7 if seed is None else seed
    scenario = ChaosScenario(
        n_nodes=5, n_turns=200, seed=seed,
        adversaries={
            0: lambda sim, i: EquivocationStorm(
                sim, i, n_branches=2, fork_every=2, start=10, end=120
            ),
        },
        attack_end=120,
    )
    sim = ChaosSimulation(
        scenario, ckpt_dir, metrics=metrics, tracer=tracer,
        flightrec=flightrec,
    )
    verdict = sim.run()
    adv = _honest_counters(sim)
    adv["strategy"] = "equivocation_storm"
    verdict["adversary"] = adv
    verdict["ok"] = bool(
        verdict["ok"]
        and adv["equivocations_detected"] > 0
        and adv["budget_exhausted"] == 0
    )
    return _with_engines(sim, verdict, engine)


@register_scenario("censorship")
def run_censorship(
    ckpt_dir: str, seed: Optional[int] = None, engine: str = "incremental",
    metrics=None, tracer=None, flightrec=None,
) -> Dict:
    """A relay censors member 1's events out of its replies for 100
    turns.  Safety/liveness must hold (the victim's events reach peers
    over other routes) and at least one honest pull must convict the
    relay (``withholding_suspected > 0``)."""
    seed = 3 if seed is None else seed
    scenario = ChaosScenario(
        n_nodes=5, n_turns=200, seed=seed,
        adversaries={
            0: lambda sim, i: CensorshipRelay(
                sim, i, victim_index=1, start=20, end=120
            ),
        },
        attack_end=120,
    )
    sim = ChaosSimulation(
        scenario, ckpt_dir, metrics=metrics, tracer=tracer,
        flightrec=flightrec,
    )
    verdict = sim.run()
    adv = _honest_counters(sim)
    adv["strategy"] = "censorship"
    verdict["adversary"] = adv
    verdict["ok"] = bool(verdict["ok"] and adv["withholding_suspected"] > 0)
    return _with_engines(sim, verdict, engine)


@register_scenario("delayed_release")
def run_delayed_release(
    ckpt_dir: str, seed: Optional[int] = None, engine: str = "incremental",
    metrics=None, tracer=None, flightrec=None,
) -> Dict:
    """A straggler holds its own events for ~110 turns — long past the
    honest frozen vote horizon — then releases the tail.  The released
    witnesses must land as ``late_witnesses`` (the deterministic expiry
    horizon registers them as full citizens) with zero
    ``horizon_violations``, and every engine must stay bit-identical."""
    seed = 5 if seed is None else seed
    scenario = ChaosScenario(
        n_nodes=5, n_turns=230, seed=seed,
        adversaries={
            0: lambda sim, i: DelayedReleaseStraggler(
                sim, i, hold_from=30, release_at=140
            ),
        },
        attack_end=140,
    )
    sim = ChaosSimulation(
        scenario, ckpt_dir, metrics=metrics, tracer=tracer,
        flightrec=flightrec,
    )
    verdict = sim.run()
    adv = _honest_counters(sim)
    adv["strategy"] = "delayed_release"
    verdict["adversary"] = adv
    verdict["ok"] = bool(
        verdict["ok"]
        and adv["late_witnesses"] > 0
        and adv["horizon_violations"] == 0
    )
    return _with_engines(sim, verdict, engine)


def _run_fork_bomb(
    ckpt_dir: str, seed: int, engine: str, n_forkers: int,
    metrics=None, tracer=None, flightrec=None,
):
    n_nodes = 7
    scenario = ChaosScenario(
        n_nodes=n_nodes, n_turns=220, seed=seed,
        adversaries={
            i: (
                lambda sim, idx: EquivocationStorm(
                    sim, idx, n_branches=2, fork_every=1, start=5, end=130
                )
            )
            for i in range(n_forkers)
        },
        attack_end=130,
    )
    sim = ChaosSimulation(
        scenario, ckpt_dir, metrics=metrics, tracer=tracer,
        flightrec=flightrec,
    )
    verdict = sim.run()
    adv = _honest_counters(sim)
    adv["n_forkers"] = n_forkers
    adv["f_budget"] = (n_nodes - 1) // 3
    verdict["adversary"] = adv
    return verdict, sim


@register_scenario("fork_bomb")
def run_fork_bomb(
    ckpt_dir: str, seed: Optional[int] = None, engine: str = "incremental",
    metrics=None, tracer=None, flightrec=None,
) -> Dict:
    """Coordinated equivocation at exactly f = (n-1)//3 creators (n=7,
    f=2): the protocol's design point.  Honest nodes must survive —
    safety, post-attack liveness, forks detected — with ZERO budget
    flags (the admission check must not cry wolf at the bound)."""
    seed = 2 if seed is None else seed
    verdict, sim = _run_fork_bomb(
        ckpt_dir, seed, engine, n_forkers=2, metrics=metrics, tracer=tracer,
        flightrec=flightrec,
    )
    adv = verdict["adversary"]
    adv["strategy"] = "fork_bomb"
    verdict["ok"] = bool(
        verdict["ok"]
        and adv["equivocations_detected"] > 0
        and adv["budget_exhausted"] == 0
    )
    return _with_engines(sim, verdict, engine)


@register_scenario("fork_bomb_overbudget")
def run_fork_bomb_overbudget(
    ckpt_dir: str, seed: Optional[int] = None, engine: str = "incremental",
    metrics=None, tracer=None, flightrec=None,
) -> Dict:
    """Coordinated equivocation at f+1 creators — OUTSIDE the n > 3f
    model.  The obligation is detection, not tolerance: every honest
    node that observes the (f+1)-th forked creator must raise its
    ``budget_exhausted`` admission flag, so a divergence (should one
    occur) is never silent.  The verdict's ``ok`` is the flag plus the
    absence of *unflagged* divergence; the safety section still reports
    what actually happened."""
    seed = 2 if seed is None else seed
    verdict, sim = _run_fork_bomb(
        ckpt_dir, seed, engine, n_forkers=3, metrics=metrics, tracer=tracer,
        flightrec=flightrec,
    )
    adv = verdict["adversary"]
    adv["strategy"] = "fork_bomb_overbudget"
    flagged = adv["budget_exhausted"] > 0
    diverged = not (
        verdict["safety"]["prefix_agree"] and verdict["safety"]["oracle_agree"]
    )
    adv["silent_divergence"] = bool(diverged and not flagged)
    verdict["ok"] = bool(flagged and not adv["silent_divergence"])
    if not verdict["ok"] and not verdict.get("flightrec_dump"):
        verdict["flightrec_dump"] = sim.flightrec_postmortem(verdict)
    return verdict


@register_scenario("horizon_storm")
def _run_horizon_storm(
    ckpt_dir: str, seed: Optional[int] = None, engine: str = "incremental",
    metrics=None, tracer=None, flightrec=None,
) -> Dict:
    """Straggler witnesses across a healing partition: late tails must
    land below the committed frontier with cross-engine bit-parity."""
    from tpu_swirld.chaos import run_horizon_storm

    return run_horizon_storm(
        ckpt_dir, seed=1 if seed is None else seed, metrics=metrics,
        tracer=tracer, engine=engine, flightrec=flightrec,
    )


@register_scenario("overflow_storm")
def _run_overflow_storm(
    ckpt_dir: str, seed: Optional[int] = None, engine: str = "incremental",
    metrics=None, tracer=None, flightrec=None,
) -> Dict:
    """Witness-table self-healing: fork-storm slot doubling and the
    unclamped round-window retry must finish with oracle parity."""
    from tpu_swirld.chaos import run_overflow_storm

    return run_overflow_storm(
        seed=4 if seed is None else seed, flightrec=flightrec
    )


@register_scenario("membership_churn")
def _run_membership_churn(
    ckpt_dir: str, seed: Optional[int] = None, engine: str = "incremental",
    metrics=None, tracer=None, flightrec=None,
) -> Dict:
    """Dynamic membership under attack: an adversary JOINs by decided
    tx, mounts an equivocation storm across the vote-out boundary, and
    is removed by a decided LEAVE — stake zeroed, witness power gone."""
    from tpu_swirld.chaos import run_membership_churn

    return run_membership_churn(
        ckpt_dir, seed=11 if seed is None else seed, flightrec=flightrec,
    )
