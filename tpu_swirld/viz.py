"""Visualization seam: state export + lightweight renderers.

The reference ships a Bokeh app plotting the hashgraph (x = member,
y = height, color = round/fame — upstream ``viz.py``, SURVEY.md §1/§2 #10)
as its de-facto debugging oracle.  This module provides the same
information dependency-free:

- :func:`export_state` — one dict per event: (creator, height, round,
  witness, famous, round received, consensus position).  Works for both
  an oracle :class:`Node` and a :class:`PackedDAG` + ``ConsensusResult``
  pair, so either backend can be inspected with identical tooling.
- :func:`to_json` — the export, serialized.
- :func:`to_dot` — a Graphviz rendering (color = round, doubled border =
  witness, filled = famous) for quick ``dot -Tsvg`` inspection.
- :func:`ascii_lanes` — a terminal sketch: one lane per member, one row
  per height, round numbers in the cells.
- :func:`fame_gauges` — per-round decided/undecided witness-fame counts,
  recordable into an :class:`~tpu_swirld.obs.registry.Registry` so one
  trace file carries both the timing spans and the DAG-shape gauges.
  ``to_dot`` / ``ascii_lanes`` annotate their output with these gauges.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple


def fame_gauges(rows: List[Dict], registry=None) -> Dict[int, Tuple[int, int]]:
    """Per-round ``(decided, undecided)`` witness-fame counts.

    ``rows`` is an :func:`export_state` export.  With ``registry=`` (an
    ``obs.Registry``), each round also lands as gauges
    ``round_fame_decided{round=r}`` / ``round_fame_undecided{round=r}``,
    joining the protocol gauges the report CLI renders.
    """
    acc: Dict[int, List[int]] = {}
    for r in rows:
        if not r["witness"] or r["round"] is None:
            continue
        cell = acc.setdefault(r["round"], [0, 0])
        cell[0 if r["famous"] is not None else 1] += 1
    gauges = {rnd: (d, u) for rnd, (d, u) in sorted(acc.items())}
    if registry is not None:
        for rnd, (d, u) in gauges.items():
            registry.gauge("round_fame_decided", {"round": str(rnd)}).set(d)
            registry.gauge("round_fame_undecided", {"round": str(rnd)}).set(u)
    return gauges


def _fame_summary(gauges: Dict[int, Tuple[int, int]], empty: str) -> str:
    return (
        " ".join(f"r{rnd}={d}/{d + u}" for rnd, (d, u) in gauges.items())
        or empty
    )


def export_state(node=None, packed=None, result=None) -> List[Dict]:
    """Per-event visualization records, in topo order."""
    if node is not None:
        rows = []
        order_pos = {e: i for i, e in enumerate(node.consensus)}
        for eid in node.order_added:
            ev = node.hg[eid]
            rows.append(
                {
                    "id": eid.hex()[:16],
                    "creator": node.member_index[ev.c],
                    "height": node.seq[eid],
                    "t": ev.t,
                    "round": node.round.get(eid),
                    "witness": bool(node.is_witness.get(eid, False)),
                    "famous": node.famous.get(eid),
                    "round_received": node.round_received.get(eid),
                    "order": order_pos.get(eid),
                    "parents": [p.hex()[:16] for p in ev.p],
                }
            )
        return rows
    if packed is None or result is None:
        raise ValueError("pass either node= or (packed=, result=)")
    order_pos = {i: k for k, i in enumerate(result.order)}
    rows = []
    for i in range(packed.n):
        rr = int(result.round_received[i])
        rows.append(
            {
                "id": packed.ids[i].hex()[:16],
                "creator": int(packed.creator[i]),
                "height": int(packed.seq[i]),
                "t": int(packed.t[i]),
                "round": int(result.round[i]),
                "witness": bool(result.is_witness[i]),
                "famous": result.famous.get(i),
                "round_received": rr if rr >= 0 else None,
                "order": order_pos.get(i),
                "parents": [
                    packed.ids[int(p)].hex()[:16]
                    for p in packed.parents[i]
                    if p >= 0
                ],
            }
        )
    return rows


def to_json(path: Optional[str] = None, **kw) -> str:
    s = json.dumps(export_state(**kw), indent=1)
    if path:
        with open(path, "w") as f:
            f.write(s)
    return s


_PALETTE = [
    "#8dd3c7", "#ffffb3", "#bebada", "#fb8072", "#80b1d3",
    "#fdb462", "#b3de69", "#fccde5", "#d9d9d9", "#bc80bd",
]


def to_dot(registry=None, **kw) -> str:
    """Graphviz: color = round, peripheries = witness, bold = famous.
    The graph label summarizes per-round fame progress (decided/undecided
    witnesses); ``registry=`` also records those gauges."""
    rows = export_state(**kw)
    gauges = fame_gauges(rows, registry=registry)
    label = "fame per round: " + _fame_summary(gauges, "(no witnesses)")
    lines = [
        "digraph hashgraph {",
        "  rankdir=BT; node [style=filled, shape=box, fontsize=9];",
        f'  labelloc="t"; label="{label}";',
    ]
    for r in rows:
        color = _PALETTE[(r["round"] or 0) % len(_PALETTE)]
        attrs = [f'fillcolor="{color}"']
        attrs.append(f'label="m{r["creator"]}h{r["height"]}\\nr{r["round"]}"')
        if r["witness"]:
            attrs.append("peripheries=2")
        if r["famous"]:
            attrs.append("penwidth=3")
        lines.append(f'  "{r["id"]}" [{", ".join(attrs)}];')
        for p in r["parents"]:
            lines.append(f'  "{r["id"]}" -> "{p}";')
    lines.append("}")
    return "\n".join(lines)


def ascii_lanes(max_height: int = 24, registry=None, **kw) -> str:
    """Terminal sketch: members as columns, heights as rows, cells show the
    round number (* witness, ! famous).  A footer summarizes per-round
    fame progress; ``registry=`` also records the gauges."""
    rows = export_state(**kw)
    n_members = max(r["creator"] for r in rows) + 1
    grid: Dict[int, Dict[int, str]] = {}
    top = 0
    for r in rows:
        h = r["height"]
        top = max(top, h)
        mark = str(r["round"] if r["round"] is not None else "?")
        if r["famous"]:
            mark += "!"
        elif r["witness"]:
            mark += "*"
        grid.setdefault(h, {})[r["creator"]] = mark
    lines = [
        "height | " + " ".join(f"m{i:<3}" for i in range(n_members)),
        "-" * (9 + 5 * n_members),
    ]
    lo = max(0, top - max_height + 1)
    for h in range(top, lo - 1, -1):
        cells = [f"{grid.get(h, {}).get(m, ''):<4}" for m in range(n_members)]
        lines.append(f"{h:6} | " + " ".join(cells))
    gauges = fame_gauges(rows, registry=registry)
    lines.append("-" * (9 + 5 * n_members))
    lines.append(
        "fame decided/witnesses per round: "
        + _fame_summary(gauges, "(none)")
    )
    return "\n".join(lines)
