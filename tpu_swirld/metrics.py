"""Metrics / observability (SURVEY.md §5).

Lightweight per-phase wall-clock counters plus the protocol-level gauges
the driver metric is built from: events ingested, events ordered
(events-to-consensus), decided-round lag, and undecided-witness backlog.
Zero overhead when disabled (the default); enable per node with
``node.metrics = Metrics()`` or pass ``metrics=`` to the engine helpers.

``jax.profiler`` traces for the device pipeline are one call away:
:func:`trace_consensus` wraps a pipeline run in a profiler trace directory
viewable with TensorBoard/XProf.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict


class Metrics:
    """Cumulative phase timers + counters."""

    def __init__(self) -> None:
        self.seconds: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.seconds[name] = self.seconds.get(name, 0.0) + (
                time.perf_counter() - t0
            )

    def count(self, name: str, delta: int = 1) -> None:
        self.counts[name] = self.counts.get(name, 0) + delta

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        out.update({f"s_{k}": round(v, 6) for k, v in self.seconds.items()})
        out.update({f"n_{k}": v for k, v in self.counts.items()})
        total = sum(
            self.seconds.get(k, 0.0)
            for k in ("divide_rounds", "decide_fame", "find_order")
        )
        ordered = self.counts.get("events_ordered", 0)
        if total > 0 and ordered:
            out["events_per_sec_to_consensus"] = round(ordered / total, 2)
        return out


def node_gauges(node) -> Dict[str, int]:
    """Protocol-level gauges for one oracle node."""
    undecided = sum(1 for f in node.famous.values() if f is None)
    return {
        "events": len(node.hg),
        "events_ordered": len(node.consensus),
        "max_round": node.max_round,
        "decided_round_lag": node.max_round - node.consensus_round,
        "undecided_witnesses": undecided,
        "orphans_parked": len(node._orphans),
        "ancient_quarantined": len(node.ancient),
    }


def trace_consensus(packed, config=None, outdir: str = "/tmp/swirld-trace", **kw):
    """Run the device pipeline under a jax.profiler trace (XProf viewable)."""
    import jax

    from tpu_swirld.tpu.pipeline import run_consensus

    with jax.profiler.trace(outdir):
        result = run_consensus(packed, config, **kw)
    return result
