"""Metrics compatibility shim over :mod:`tpu_swirld.obs` (SURVEY.md §5).

The real observability subsystem lives in :mod:`tpu_swirld.obs` (nested-span
tracer, counter/gauge/histogram registry, Prometheus/JSON exporters, report
CLI).  This module keeps the original lightweight surface — ``Metrics`` with
``phase`` / ``count`` / ``snapshot``, :func:`node_gauges`,
:func:`trace_consensus` — as a thin shim so existing call sites keep working
unchanged; a ``Metrics`` now records into an :class:`~tpu_swirld.obs.
registry.Registry` (own or shared), so per-node counters and the ambient
pipeline metrics can export through one Prometheus/JSON pipe.

Zero overhead when disabled (the default); enable per node with
``node.metrics = Metrics()`` or pass ``metrics=`` / ``tracer=`` to the
:mod:`tpu_swirld.sim` helpers.

``jax.profiler`` traces for the device pipeline are one call away:
:func:`trace_consensus` wraps a pipeline run in a profiler trace directory
viewable with TensorBoard/XProf.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional

from tpu_swirld.obs.registry import Counter, Registry

PHASE_METRIC = "phase_seconds"


class Metrics:
    """Cumulative phase timers + counters (registry-backed shim).

    ``seconds`` / ``counts`` remain available as dict views derived from
    the registry, so pre-obs consumers (and ``tests/test_aux.py``) see the
    original shape.
    """

    def __init__(self, registry: Optional[Registry] = None) -> None:
        self.registry = registry if registry is not None else Registry()

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.registry.counter(PHASE_METRIC, {"phase": name}).inc(
                time.perf_counter() - t0
            )

    def count(self, name: str, delta: int = 1) -> None:
        # the pre-obs surface accepted any delta (plain dict addition);
        # keep that contract — bypass Counter.inc's monotonic guard
        self.registry.counter(name).value += delta

    @property
    def seconds(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for labels, m in self.registry.collect(PHASE_METRIC).items():
            d = dict(labels)
            if "phase" in d:            # ignore non-phase variants
                out[d["phase"]] = m.value
        return out

    @property
    def counts(self) -> Dict[str, int]:
        return {
            m.name: int(m.value)
            for m in self.registry.metrics()
            if isinstance(m, Counter)
            and not m.labels
            and m.name != PHASE_METRIC
        }

    def snapshot(self) -> Dict[str, float]:
        seconds = self.seconds
        counts = self.counts
        out: Dict[str, float] = {}
        out.update({f"s_{k}": round(v, 6) for k, v in seconds.items()})
        out.update({f"n_{k}": v for k, v in counts.items()})
        total = sum(
            seconds.get(k, 0.0)
            for k in ("divide_rounds", "decide_fame", "find_order")
        )
        ordered = counts.get("events_ordered", 0)
        if total > 0 and ordered:
            out["events_per_sec_to_consensus"] = round(ordered / total, 2)
        return out


def node_gauges(
    node,
    registry: Optional[Registry] = None,
    node_label: Optional[str] = None,
) -> Dict[str, int]:
    """Protocol-level gauges for one oracle node.

    Robust to partially-shaped nodes (checkpoint-restored or backend-engine
    nodes may lack optional attributes): every read goes through the public
    surface (``node.orphans_parked`` / ``node.forks_detected``) or a
    ``getattr`` default.  With ``registry=``, each gauge is also recorded
    as ``node_<name>{node=...}`` — labelled by ``node_label`` (default: the
    node's pk prefix) so exporting a whole population into one shared
    registry keeps every node distinct.
    """
    famous = getattr(node, "famous", {})
    undecided = sum(1 for f in famous.values() if f is None)
    max_round = getattr(node, "max_round", 0)
    gauges = {
        "events": len(getattr(node, "hg", ())),
        "events_ordered": len(getattr(node, "consensus", ())),
        "max_round": max_round,
        "decided_round_lag": max_round - getattr(node, "consensus_round", 0),
        "undecided_witnesses": undecided,
        "orphans_parked": getattr(node, "orphans_parked", 0),
        # admission-control gauge: the tx ingestion layer sheds client
        # submissions while this exceeds its configured threshold
        "undecided_window": getattr(node, "undecided_window", 0),
        "late_witnesses": len(getattr(node, "late_witnesses", ())),
        "horizon_violations": getattr(node, "horizon_violations", 0),
        "forks_detected": getattr(node, "forks_detected", 0),
        "equivocations_detected": getattr(node, "equivocations_detected", 0),
        "withholding_suspected": getattr(node, "withholding_suspected", 0),
        "budget_exhausted": getattr(node, "budget_exhausted", 0),
        "sync_branches_capped": getattr(node, "sync_branches_capped", 0),
        "bad_replies": getattr(node, "bad_replies", 0),
        "bad_requests": getattr(node, "bad_requests", 0),
        "retries": getattr(node, "retries", 0),
        "backoff_total": getattr(node, "backoff_total", 0.0),
        "quarantined_peers": getattr(node, "quarantined_peers", 0),
        "circuit_opens": getattr(node, "circuit_opens", 0),
        # finality surface: the decided frontier (consensus length) and
        # the last round whose order is committed
        "decided_watermark": len(getattr(node, "consensus", ())),
        "decided_round": getattr(node, "consensus_round", 0) - 1,
        # dynamic-membership surface (membership/): a static node reports
        # the trivial single-epoch values, so dashboards read one schema
        "membership_epoch": getattr(node, "membership_epoch", 0),
        "members_active": getattr(
            node, "members_active", len(getattr(node, "members", ()))
        ),
        "stake_total": getattr(
            node, "stake_total", getattr(node, "tot_stake", 0)
        ),
    }
    if registry is not None:
        if node_label is None:
            pk = getattr(node, "pk", None)
            node_label = pk[:4].hex() if isinstance(pk, bytes) else None
        labels = {"node": node_label} if node_label is not None else None
        for k, v in gauges.items():
            registry.gauge(f"node_{k}", labels).set(v)
        # also published under the finality_* family so the report CLI's
        # finality section shows per-node watermarks without node_ noise
        registry.gauge("finality_decided_watermark", labels).set(
            gauges["decided_watermark"]
        )
        registry.gauge("finality_decided_round", labels).set(
            gauges["decided_round"]
        )
    return gauges


def trace_consensus(packed, config=None, outdir: str = "/tmp/swirld-trace", **kw):
    """Run the device pipeline under a jax.profiler trace (XProf viewable)."""
    import jax

    from tpu_swirld.tpu.pipeline import run_consensus

    with jax.profiler.trace(outdir):
        result = run_consensus(packed, config, **kw)
    return result
