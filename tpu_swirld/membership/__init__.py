"""Dynamic membership: consensus-agreed, epoch-versioned member sets.

Submodules:

- ``txs``      — the ``MTX1`` membership-transaction wire format
                 (join / leave / restake payloads riding ordinary events);
- ``epoch``    — :class:`MemberEpoch` / :class:`EpochLedger`: the
                 append-only, consensus-derived epoch sequence;
- ``dynamic``  — :class:`DynamicNode`, the oracle engine with per-round
                 epoch stake, gossip pre-admission, and deterministic
                 restatement;
- ``repack``   — the member-axis repack pass at epoch activation;
- ``engine``   — :func:`run_dynamic` drivers for all five engines;
- ``sim``      — dynamic-population gossip simulations + churn schedules.
"""

from tpu_swirld.membership.epoch import (
    DEFAULT_DELAY,
    EpochLedger,
    MemberEpoch,
    activation_round,
    ledger_from_decided,
)
from tpu_swirld.membership.txs import (
    JOIN,
    LEAVE,
    RESTAKE,
    MembershipTx,
    decode_tx,
    encode_tx,
    join_payload,
    leave_payload,
    restake_payload,
)

__all__ = [
    "DEFAULT_DELAY",
    "EpochLedger",
    "MemberEpoch",
    "MembershipTx",
    "JOIN",
    "LEAVE",
    "RESTAKE",
    "activation_round",
    "decode_tx",
    "encode_tx",
    "join_payload",
    "leave_payload",
    "ledger_from_decided",
    "restake_payload",
]
