"""Gossip simulations with dynamic membership.

Mirrors :mod:`tpu_swirld.sim` (same key derivation, same shared-clock
population bootstrap) but builds :class:`DynamicNode` populations and
adds the two schedule shapes the membership suites need:

- :func:`make_dynamic_simulation` — a population of dynamic nodes with a
  per-turn payload hook, so membership transactions ride ordinary gossip
  events at scripted turns;
- :func:`churn_schedule` — a canonical multi-epoch event schedule (a
  leave then a join, decided rounds apart → ≥2 epoch transitions) plus
  the genesis member/stake vectors, for the cross-engine parity and
  bench/soak harnesses.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from tpu_swirld import crypto
from tpu_swirld.config import SwirldConfig
from tpu_swirld.membership.dynamic import DynamicNode, joining_node
from tpu_swirld.membership.txs import join_payload, leave_payload, restake_payload
from tpu_swirld.sim import build_population


@dataclasses.dataclass
class DynamicSimulation:
    """A population of :class:`DynamicNode` plus the shared network."""

    config: SwirldConfig
    nodes: List[DynamicNode]
    network: Dict[bytes, Callable]
    network_want: Dict[bytes, Callable]
    rng: random.Random
    clock: List[int]
    #: turn -> payload to ride the syncing node's next event (consumed)
    tx_schedule: Dict[int, bytes] = dataclasses.field(default_factory=dict)
    turn: int = 0

    @property
    def members(self) -> List[bytes]:
        return [n.pk for n in self.nodes]

    def step(self, node_i: Optional[int] = None) -> List[bytes]:
        self.clock[0] += 1
        t = self.turn
        self.turn += 1
        if node_i is None:
            node_i = self.rng.randrange(len(self.nodes))
        node = self.nodes[node_i]
        peers = [n.pk for n in self.nodes if n.pk != node.pk]
        if not peers:
            return []
        peer = peers[self.rng.randrange(len(peers))]
        payload = self.tx_schedule.pop(t, b"")
        new_ids = node.sync(peer, payload)
        node.consensus_pass(new_ids)
        return new_ids

    def run(self, n_turns: int) -> None:
        for _ in range(n_turns):
            self.step()

    def add_joiner(self, sk: bytes, pk: bytes) -> DynamicNode:
        """Bring a not-yet-decided member online: it self-admits for
        gossip and participates; stake arrives when its JOIN decides."""
        jn = joining_node(
            sk, pk, self.network, list(self.members), self.config,
            clock=lambda: self.clock[0], network_want=self.network_want,
        )
        self.network[pk] = jn.ask_sync
        self.network_want[pk] = jn.ask_events
        self.nodes.append(jn)
        return jn


def make_dynamic_simulation(
    n_nodes: int,
    seed: int = 0,
    config: Optional[SwirldConfig] = None,
    tx_schedule: Optional[Dict[int, bytes]] = None,
) -> DynamicSimulation:
    """Same population bootstrap as :func:`tpu_swirld.sim.make_simulation`
    (identical keys for a given seed) with :class:`DynamicNode` members."""
    config = config or SwirldConfig(n_members=n_nodes, seed=seed)
    if config.n_members != n_nodes:
        raise ValueError("config.n_members != n_nodes")
    pop = build_population(n_nodes, seed)
    nodes: List[DynamicNode] = []
    for pk, sk in pop.keys:
        node = DynamicNode(
            sk=sk, pk=pk, network=pop.network, members=pop.members,
            config=config, clock=lambda: pop.clock[0],
            network_want=pop.network_want,
        )
        pop.network[pk] = node.ask_sync
        pop.network_want[pk] = node.ask_events
        nodes.append(node)
    return DynamicSimulation(
        config=config, nodes=nodes, network=pop.network,
        network_want=pop.network_want, rng=pop.rng, clock=pop.clock,
        tx_schedule=dict(tx_schedule or {}),
    )


def churn_schedule(
    n_nodes: int = 4,
    seed: int = 0,
    turns: int = 700,
    leave_at: int = 30,
    join_at: int = 260,
    join_stake: int = 2,
    config: Optional[SwirldConfig] = None,
):
    """A canonical multi-epoch schedule: member ``n-1`` leaves, then a
    fresh key joins, turns apart so the two transactions decide in
    different rounds (≥ 2 epoch transitions).

    Returns ``(events, members, stake, sim)`` where ``events`` is node
    0's DAG in insertion (topo) order — the input shape
    :func:`tpu_swirld.membership.engine.run_dynamic` consumes — and
    ``sim`` is the finished simulation for further inspection.
    """
    config = config or SwirldConfig(n_members=n_nodes, seed=seed)
    jpk, jsk = crypto.keypair(b"churn-joiner-%d" % seed)
    sim = make_dynamic_simulation(
        n_nodes, seed=seed, config=config,
        tx_schedule={
            leave_at: leave_payload(sim_member(n_nodes, seed, n_nodes - 1)),
            join_at: join_payload(jpk, join_stake),
        },
    )
    sim.run(turns)
    node = sim.nodes[0]
    events = [node.hg[e] for e in node.order_added]
    stake = list(node._genesis_stake)
    return events, list(node._genesis_members), stake, sim


def sim_member(n_nodes: int, seed: int, i: int) -> bytes:
    """The i-th member pk for ``(n_nodes, seed)`` (sim key derivation)."""
    from tpu_swirld.sim import member_keys

    return member_keys(n_nodes, seed)[i][0]
