"""Dynamic-membership drivers for the five consensus engines.

One entry point — :func:`run_dynamic` — replays a topologically ordered
event schedule under consensus-decided membership, in the ingest
granularity of each engine:

- ``oracle``       one consensus pass per event (the live-gossip shape);
- ``batch``        a single pass over the full DAG;
- ``incremental``  chunked passes with carried state;
- ``streaming``    chunked passes + decided rows stamped with their
                   epoch id (the archive schema);
- ``mesh``         chunked passes + row-shard re-pin bookkeeping across
                   the member-axis change.

Decisions come from the epoch-aware restatement core — a
:class:`~tpu_swirld.membership.dynamic.DynamicNode` observer replay —
which is *the* semantics every engine follows.  The per-engine value is
twofold: the different pass granularities exercise the incremental /
batch determinism of the dynamic semantics (a memoization or adoption
bug shows up as a granularity-dependent order), and each driver performs
its engine's structural work at every epoch boundary: the member-axis
repack of the live packer (``membership.repack``), the epoch stamp on
archived decided rows, and the shard re-pin map for the mesh window.

When the schedule decides **no** membership transaction (a single-epoch
run), each device driver additionally runs its real engine —
``run_consensus`` / ``IncrementalConsensus`` / ``StreamingConsensus`` /
``MeshStreamingConsensus`` — over the same DAG and cross-checks the
native order bit-for-bit against the observer's.  That is the
regression pin: equal-stake single-epoch dynamic runs are byte-identical
to the pre-membership engines.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from tpu_swirld.config import SwirldConfig
from tpu_swirld.membership.dynamic import DynamicNode
from tpu_swirld.membership.epoch import EpochLedger
from tpu_swirld.membership.repack import RepackStats, repack_packer
from tpu_swirld.packing import Packer

ENGINES = ("oracle", "batch", "incremental", "streaming", "mesh")


@dataclasses.dataclass
class DynamicResult:
    """Engine-independent view of a dynamic-membership run."""

    engine: str
    order: List[bytes]                  # decided event ids, consensus order
    rounds: Dict[bytes, int]            # event id -> round
    witnesses: Dict[bytes, bool]        # event id -> witness flag
    ledger: EpochLedger
    restatements: int
    repacks: List[RepackStats]
    single_epoch: bool
    #: engine-native cross-check result (single-epoch runs only)
    native_order: Optional[List[bytes]] = None
    #: streaming: decided rows stamped (event id, epoch id of the round
    #: that received them); mesh: member -> shard re-pin map per epoch
    archive_epochs: Optional[List[Tuple[bytes, int]]] = None
    shard_pins: Optional[List[Dict[bytes, int]]] = None

    @property
    def epochs(self) -> int:
        return len(self.ledger.epochs)


def _observer(
    members: Sequence[bytes], stake: Sequence[int], config: SwirldConfig
) -> DynamicNode:
    pk, sk = members[0], b"\x00" * 32
    return DynamicNode(
        sk=sk, pk=pk, network={}, members=list(members), config=config,
        create_genesis=False, network_want={},
    )


def _chunks(n: int, size: int) -> List[Tuple[int, int]]:
    size = max(1, size)
    return [(s, min(n, s + size)) for s in range(0, n, size)]


def run_dynamic(
    events,
    members: Sequence[bytes],
    stake: Sequence[int],
    config: Optional[SwirldConfig] = None,
    *,
    engine: str = "batch",
    chunk: int = 256,
    mesh=None,
    n_shards: int = 2,
    cross_check: bool = True,
) -> DynamicResult:
    """Run one engine's dynamic-membership driver over ``events``
    (topologically ordered, genesis events included)."""
    if engine not in ENGINES:
        raise ValueError(f"unknown engine {engine!r}; one of {ENGINES}")
    config = config or SwirldConfig(
        n_members=len(members), stake=tuple(stake)
    )
    events = list(events)

    # --- decisions: the epoch-aware restatement core, in this engine's
    # ingest granularity
    node = _observer(members, stake, config)
    if engine == "oracle":
        spans = _chunks(len(events), 1)
    elif engine == "batch":
        spans = _chunks(len(events), max(1, len(events)))
    else:
        spans = _chunks(len(events), chunk)
    for lo, hi in spans:
        new_ids = []
        for ev in events[lo:hi]:
            if node.add_event(ev):
                new_ids.append(ev.id)
        node.consensus_pass(new_ids)

    single_epoch = len(node.ledger.epochs) == 1

    # --- structural work per epoch boundary: live-packer member-axis
    # repack (all device engines), epoch-stamped archive rows
    # (streaming), shard re-pin maps (mesh)
    repacks: List[RepackStats] = []
    archive_epochs: Optional[List[Tuple[bytes, int]]] = None
    shard_pins: Optional[List[Dict[bytes, int]]] = None
    if engine != "oracle":
        packer = Packer(list(members), list(stake))
        for epoch in node.ledger.epochs[1:]:
            repacks.append(repack_packer(packer, epoch))
        for ev in events:
            if ev.c in packer.member_index:
                packer.append(ev)
    if engine == "streaming":
        archive_epochs = [
            (x, node.ledger.epoch_at(node.round_received[x]).epoch_id)
            for x in node.consensus
        ]
    if engine == "mesh":
        shard_pins = []
        for epoch in node.ledger.epochs:
            shard_pins.append({
                m: i % max(1, n_shards)
                for i, m in enumerate(epoch.members)
            })

    # --- single-epoch cross-check against the real engine
    native_order: Optional[List[bytes]] = None
    if single_epoch and engine != "oracle" and cross_check:
        native_order = _native_order(
            events, members, stake, config,
            engine=engine, chunk=chunk, mesh=mesh,
        )
        if native_order != node.consensus:
            raise AssertionError(
                f"single-epoch {engine} engine diverged from the "
                f"dynamic core: {len(native_order)} vs "
                f"{len(node.consensus)} decided"
            )

    return DynamicResult(
        engine=engine,
        order=list(node.consensus),
        rounds={e: node.round[e] for e in node.order_added},
        witnesses={e: bool(node.is_witness[e]) for e in node.order_added},
        ledger=node.ledger,
        restatements=node.restatements,
        repacks=repacks,
        single_epoch=single_epoch,
        native_order=native_order,
        archive_epochs=archive_epochs,
        shard_pins=shard_pins,
    )


def _native_order(
    events, members, stake, config, *, engine, chunk, mesh
) -> List[bytes]:
    """The unmodified engine's decided order (ids) for a single-epoch
    schedule — the byte-identical regression pin."""
    from tpu_swirld.packing import pack_events

    packed = pack_events(events, list(members), list(stake))
    if engine == "batch":
        from tpu_swirld.tpu.pipeline import run_consensus

        res = run_consensus(packed, config)
        return [packed.ids[i] for i in res.order]
    if engine == "incremental":
        from tpu_swirld.tpu.pipeline import IncrementalConsensus

        inc = IncrementalConsensus(
            list(members), list(stake), config, chunk=max(32, chunk)
        )
        for lo, hi in _chunks(len(events), chunk):
            inc.ingest(events[lo:hi])
        res = inc.result()
        return [packed.ids[i] for i in res.order]
    if engine in ("streaming", "mesh"):
        if engine == "mesh" and mesh is not None:
            from tpu_swirld.parallel import MeshStreamingConsensus

            inc = MeshStreamingConsensus(
                mesh, list(members), list(stake), config,
                chunk=max(32, chunk),
            )
        else:
            from tpu_swirld.store.streaming import StreamingConsensus

            inc = StreamingConsensus(
                list(members), list(stake), config, chunk=max(32, chunk)
            )
        for lo, hi in _chunks(len(events), chunk):
            inc.ingest(events[lo:hi])
        res = inc.result()
        return [packed.ids[i] for i in res.order]
    raise ValueError(engine)


def run_all_engines(
    events,
    members: Sequence[bytes],
    stake: Sequence[int],
    config: Optional[SwirldConfig] = None,
    *,
    chunk: int = 64,
    engines: Sequence[str] = ENGINES,
    **kw,
) -> Dict[str, DynamicResult]:
    """Cross-engine parity harness: run every engine's dynamic driver
    over one schedule and verify bit-identical order + rounds."""
    results = {
        e: run_dynamic(
            events, members, stake, config, engine=e, chunk=chunk, **kw
        )
        for e in engines
    }
    ref = results[list(engines)[0]]
    for e, res in results.items():
        if res.order != ref.order:
            raise AssertionError(
                f"engine {e} order diverges from {ref.engine}: "
                f"{len(res.order)} vs {len(ref.order)} decided"
            )
        if res.rounds != ref.rounds:
            raise AssertionError(
                f"engine {e} rounds diverge from {ref.engine}"
            )
        if not res.ledger.same_epochs(ref.ledger):
            raise AssertionError(
                f"engine {e} ledger diverges from {ref.engine}"
            )
    return results
