"""MemberEpoch repack: remap the member axis at an epoch boundary.

The union-registry invariant (``membership.epoch``) makes this pass
cheap by construction: member indices never change, joins *append* rows,
leaves keep their rows with stake zeroed.  So an epoch repack is

- **host side**: extend the live :class:`~tpu_swirld.packing.Packer`
  with the new member rows (``add_member``) and swap its stake vector
  (``set_stake``) — the anc/sees slabs, ssm column store, witness
  tables, and fork-pair ledgers are event- or (round, slot)-indexed and
  survive untouched;
- **device side**: one jitted stage (:func:`repack_stage`) that pads the
  ``(M, K)`` member table with fresh ``-1`` rows and emplaces the new
  epoch's stake vector.  The stage is registered with the flow-audit
  spec catalog (``analysis.flow.stages``) so the scale audit covers its
  memory envelope like every other pipeline stage.

Cost model (README "Dynamic membership & stake"): O(M' · K) int32 for
the member table copy plus O(M') for the stake swap — independent of
the event count, so repack latency is flat while ev/s scales.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_swirld.membership.epoch import MemberEpoch
from tpu_swirld.packing import Packer


@functools.partial(jax.jit, static_argnames=("n_members_new",))
def repack_stage(member_table, stake_new, *, n_members_new: int):
    """Device member-axis extension: pad ``member_table`` from ``(M, K)``
    to ``(n_members_new, K)`` with ``-1`` rows (new members own no packed
    events yet) and return it alongside the new epoch's stake vector.

    Shapes are static per (M, M', K) triple, so a steady churn rate hits
    the jit cache after one compile per epoch-size bucket.
    """
    m, k = member_table.shape
    pad = n_members_new - m
    table = jnp.concatenate(
        [
            member_table,
            jnp.full((pad, k), -1, dtype=member_table.dtype),
        ],
        axis=0,
    ) if pad > 0 else member_table
    return table, jnp.asarray(stake_new, dtype=jnp.int32)


@dataclasses.dataclass(frozen=True)
class RepackStats:
    """One epoch boundary's member-axis repack, for bench/obs."""

    epoch_id: int
    activation_round: int
    members_before: int
    members_after: int
    rows_added: int
    seconds: float

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def repack_packer(packer: Packer, epoch: MemberEpoch) -> RepackStats:
    """Apply ``epoch`` to a live packer: append the joined members'
    rows, swap the stake vector, and run the device stage so the padded
    member table + stake land on the accelerator the same way the
    pipeline's ``prepare_inputs`` ships them."""
    t0 = time.perf_counter()
    before = len(packer.members)
    for pk in epoch.members:
        if pk not in packer.member_index:
            packer.add_member(pk)
    after = len(packer.members)
    if after != len(epoch.members):
        raise ValueError(
            "epoch registry is not an extension of the packer's members "
            "(union-registry invariant violated)"
        )
    packer.set_stake(epoch.stake)
    # device-side extension: same arrays pack() would snapshot, and
    # dispatched through obs.stage_call so the dispatch profiler and
    # the flow-audit coverage probe see the boundary like any other
    # pipeline stage
    from tpu_swirld import obs

    k = max(int(packer._member_counts.max(initial=0)), 1)
    table = packer._member_table[:before, :k]
    new_table, new_stake = obs.stage_call(
        "membership.repack_stage",
        repack_stage,
        np.ascontiguousarray(table),
        np.asarray(epoch.stake, dtype=np.int32),
        n_members_new=after,
    )
    if new_table.shape != (after, k) or new_stake.shape != (after,):
        raise AssertionError("repack stage shape mismatch")
    return RepackStats(
        epoch_id=epoch.epoch_id,
        activation_round=epoch.activation_round,
        members_before=before,
        members_after=after,
        rows_added=after - before,
        seconds=time.perf_counter() - t0,
    )
