"""Dynamic-membership oracle engine: :class:`DynamicNode`.

A :class:`~tpu_swirld.oracle.node.Node` whose member set is the
consensus-decided, epoch-versioned quantity of
:mod:`tpu_swirld.membership.epoch`.  Single-epoch behaviour (no decided
membership transactions) is bit-identical to the base node with the same
stake vector; everything below only engages once a ``MTX1`` payload
decides.

Semantics (the spec every engine follows):

- **Per-round stake.**  Every supermajority in rounds/fame/ordering is
  taken against the stake of the epoch governing the relevant round:
  witness promotion *into* round ``r+1`` and ``strongly_sees(x, w)`` for
  a round-``r`` witness ``w`` use ``epoch_at(r)``; a fame tally at voting
  round ``ry`` counts the round-``ry-1`` witnesses' creators at
  ``epoch_at(ry-1)``.  No tally ever mixes two epochs — the mc checker's
  epoch-purity invariant is this property made falsifiable.
- **Witness gating.**  A creator with zero stake in ``epoch_at(r)`` is
  never a round-``r`` witness.  Joiners' pre-activation events (and
  leavers' post-departure events) still enter the DAG and still get
  ordered — they just carry no voting power, which is exactly how the
  whitepaper's stake weighting generalizes the count quorum.
- **Activation.**  A tx decided in round ``rd`` (the ``round_received``
  of its carrier event) activates at ``rd + membership_delay``.  With
  the default delay, honest gossip decides fame well before events reach
  the activation round, so the incremental path simply adopts the epoch.
- **Restatement.**  If a decided tx's activation round is at or below a
  round this node has *already assigned* (possible under extreme lag or
  an adversarial schedule), incremental adoption would be order-
  dependent.  The node instead restates: a full deterministic recompute
  of rounds/fame/order from its own DAG, iterated to a ledger fixpoint
  from the genesis epoch.  The final state is thereby a pure function of
  the DAG — nodes with different arrival orders (and nodes that never
  needed to restate) converge on identical state, which the parity and
  mc suites pin.
- **Gossip admission.**  Seeing a JOIN payload (decided or not)
  pre-admits the subject key for *gossip only*: its events validate,
  park, and relay, but it holds no stake until its epoch activates.  The
  sync height vector covers the decided registry prefix (consensus-
  ordered, so positionally consistent across nodes — parsed prefix-
  tolerantly); pending members' events ship wholesale until the join
  decides.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from tpu_swirld import crypto
from tpu_swirld.config import SwirldConfig
from tpu_swirld.oracle.event import Event
from tpu_swirld.oracle.node import Node
from tpu_swirld.membership.epoch import (
    DEFAULT_DELAY,
    EpochLedger,
    activation_round,
)
from tpu_swirld.membership.txs import JOIN, decode_tx

#: restatement fixpoint cap: each iteration is a full recompute, and the
#: ledger grows monotonically per iteration, so honest runs converge in
#: two.  Past the cap the last iterate is kept — still a pure function
#: of the DAG, so every node lands on the same state.
MAX_RESTATES = 8


class DynamicNode(Node):
    """Oracle node with a consensus-decided, epoch-versioned member set."""

    def __init__(
        self,
        sk: bytes,
        pk: bytes,
        network: Dict[bytes, Callable],
        members: Sequence[bytes],
        config: Optional[SwirldConfig] = None,
        clock: Optional[Callable[[], int]] = None,
        create_genesis: bool = True,
        network_want: Optional[Dict[bytes, Callable]] = None,
        transport=None,
    ):
        config = config or SwirldConfig(n_members=len(members))
        if config.backend != "python":
            raise ValueError(
                "DynamicNode drives the oracle engine; device engines go "
                "through tpu_swirld.membership.engine"
            )
        # membership state must exist before super().__init__ runs: the
        # base constructor mints + rounds the genesis event through the
        # overridden consensus methods below
        self.membership_delay = int(
            getattr(config, "membership_delay", DEFAULT_DELAY)
        )
        self._genesis_members: Tuple[bytes, ...] = tuple(members)
        self._genesis_stake: Tuple[int, ...] = tuple(config.stakes())
        self.ledger: EpochLedger = EpochLedger.genesis(
            self._genesis_members, self._genesis_stake
        )
        self._next_ledger: Optional[EpochLedger] = None
        self._restating = False
        self.restatements = 0              # bench/obs: full recomputes
        self.repacks = 0                   # bench/obs: member-axis extensions
        self._seen_joins: Dict[bytes, int] = {}   # pk -> requested stake
        self.pending_members: Dict[bytes, int] = {}  # pk -> first-seen order
        self.fame_epoch_log: List[Tuple[bytes, int, int]] = []
        #   (witness id, voting round ry, epoch id whose stake was tallied)
        super().__init__(
            sk=sk, pk=pk, network=network, members=members, config=config,
            clock=clock, create_genesis=False, network_want=network_want,
            transport=transport,
        )
        if pk not in self.member_index:
            # a joining node: not yet in the decided registry; self-admit
            # for gossip so our own events (starting with genesis) exist
            self._note_pending(pk, 0)
        if create_genesis:
            genesis = Event(d=b"", p=(), t=self._now(), c=pk).signed(sk)
            self.add_event(genesis)
            self.divide_rounds([genesis.id])

    # --------------------------------------------------- stake addressing

    def _stake_at(self, pk: bytes, r: int) -> int:
        return self.ledger.stake_at(pk, r)

    def _tot_at(self, r: int) -> int:
        return self.ledger.total_at(r)

    def _activation_round(self, round_received: int) -> int:
        """Seam for the mc checker's activation-skew mutation; the
        epoch-purity invariant checks the ledger this builds against the
        canonical :func:`~tpu_swirld.membership.epoch.activation_round`."""
        return activation_round(round_received, self.membership_delay)

    # ---------------------------------------------------- gossip admission

    def _admit_gossip(self, pk: bytes) -> None:
        if pk in self.member_mask:
            return
        self.member_mask[pk] = 0
        self.member_events[pk] = []
        self.member_chain[pk] = []
        self.by_seq[pk] = {}
        self.branch_tips[pk] = set()
        self.fork_groups[pk] = {}
        self.has_fork[pk] = False

    def _note_pending(self, pk: bytes, stake: int) -> None:
        self._seen_joins.setdefault(pk, int(stake))
        if pk not in self.member_index and pk not in self.pending_members:
            self.pending_members[pk] = len(self.pending_members)
            self._admit_gossip(pk)

    def _known_creator(self, pk: bytes) -> bool:
        return pk in self.member_index or pk in self.pending_members

    def is_valid_event(self, ev: Event) -> bool:
        from tpu_swirld.oracle.event import MAX_KEY, MAX_PAYLOAD

        if len(ev.d) > MAX_PAYLOAD or len(ev.c) > MAX_KEY:
            return False
        if not self._known_creator(ev.c):
            return False
        if not ev.verify():
            return False
        if len(ev.p) not in (0, 2):
            return False
        if ev.p:
            sp, op = ev.p
            if sp not in self.hg or op not in self.hg:
                return False
            if self.hg[sp].c != ev.c:
                return False
            if self.hg[op].c == ev.c:
                return False
        return True

    def _plausible(self, ev: Event) -> bool:
        from tpu_swirld.oracle.event import MAX_KEY, MAX_PAYLOAD

        return (
            len(ev.d) <= MAX_PAYLOAD
            and len(ev.c) <= MAX_KEY
            and self._known_creator(ev.c)
            and ev.verify()
        )

    def add_event(self, ev: Event) -> bool:
        # a JOIN payload pre-admits its subject for gossip the moment any
        # carrier event lands (decided or not) — ingest before admission
        # would reject the joiner's events as unknown-creator
        tx = decode_tx(ev.d)
        if tx is not None and tx.kind == JOIN:
            self._note_pending(tx.pk, tx.stake)
        added = super().add_event(ev)
        return added

    def heights(self) -> Dict[bytes, int]:
        return {m: len(self.member_events[m]) for m in self.members}

    def ask_sync(self, from_pk: bytes, signed_heights: bytes) -> bytes:
        """Prefix-tolerant sync serve (see the base method for the fork
        digest rationale).  The height vector covers the asker's decided
        registry prefix — ours may be longer or shorter, so the vector is
        matched positionally against our registry: missing entries read
        as 0, surplus entries (members the asker decided before us) are
        ignored.  Events by gossip-pending creators ship wholesale."""
        if not self._known_creator(from_pk):
            raise ValueError("unknown sync peer")
        if (
            len(signed_heights) < crypto.SIG_BYTES
            or len(signed_heights) > self.config.max_reply_bytes
        ):
            self.bad_requests += 1
            raise ValueError("truncated or oversized sync request")
        payload = signed_heights[: -crypto.SIG_BYTES]
        sig = signed_heights[-crypto.SIG_BYTES:]
        if not crypto.verify(payload, sig, from_pk, crypto.DOMAIN_SYNC_REQ):
            self.bad_requests += 1
            raise ValueError("bad sync-request signature")
        if len(payload) % 4 != 0:
            self.bad_requests += 1
            raise ValueError("malformed sync-request height vector")
        heights: Dict[bytes, int] = {}
        off = 0
        for m in self.members:
            if off + 4 <= len(payload):
                heights[m] = int.from_bytes(payload[off : off + 4], "little")
            else:
                heights[m] = 0
            off += 4
        missing: List[bytes] = []
        for m in self.members:
            known = self.member_events[m]
            if not self.has_fork[m]:
                missing.extend(known[heights[m]:])
                continue
            miss = max(len(known) - heights[m], 0)
            extra: set = set()
            tips = sorted(self.branch_tips[m])
            cap = max(1, self.config.max_fork_branches)
            if len(tips) > cap:
                self.sync_branches_capped += 1
                if self.metrics is not None:
                    self.metrics.count("gossip_sync_branches_capped")
                tips = tips[:cap]
            for tip in tips:
                cur: Optional[bytes] = tip
                for _ in range(miss + 1):
                    if cur is None or cur in extra:
                        break
                    extra.add(cur)
                    cur = self.hg[cur].self_parent
            first_seq = min(self.fork_groups[m])
            extra.update(self.fork_groups[m][first_seq])
            missing.extend(sorted(extra))
        for pk in sorted(self.pending_members, key=self.pending_members.get):
            missing.extend(self.member_events.get(pk, []))
        return self._sign_event_blob(missing)

    def sync(self, peer_pk: bytes, payload: bytes) -> List[bytes]:
        new_ids = self.pull(peer_pk)
        peer_events = self.member_events.get(peer_pk, [])
        if not peer_events:
            return new_ids
        mine = self.new_event(payload, peer_events[-1])
        self.add_event(mine)
        new_ids.append(mine.id)
        return new_ids

    # -------------------------------------------------- consensus (epochal)

    def strongly_sees(self, x: bytes, w: bytes) -> bool:
        if not self.in_anc(x, w):
            return False
        key = (x, w)
        memo = self._ss_memo.get(key)
        if memo is not None:
            return memo
        epoch = self.ledger.epoch_at(self.round[w])
        amount = 0
        for m, s in zip(epoch.members, epoch.stake):
            if s > 0 and self._sees_through(x, w, m):
                amount += s
        result = 3 * amount > 2 * epoch.total_stake
        self._ss_memo[key] = result
        return result

    def divide_rounds(self, new_ids: Iterable[bytes]) -> None:
        for eid in new_ids:
            ev = self.hg[eid]
            if not ev.p:
                self.round[eid] = 0
                if self._stake_at(ev.c, 0) > 0:
                    self._register_witness(eid, 0)
                else:
                    self.is_witness[eid] = False
                continue
            sp, op = ev.p
            r = self._parent_round(sp, op)
            amount = 0
            for c, wids in self.witnesses.get(r, {}).items():
                if any(self.strongly_sees(eid, w) for w in wids):
                    amount += self._stake_at(c, r)
            if 3 * amount > 2 * self._tot_at(r):
                r += 1
            self.round[eid] = r
            self.max_round = max(self.max_round, r)
            if self.round[sp] < r and self._stake_at(ev.c, r) > 0:
                self._register_witness(eid, r)
            else:
                self.is_witness[eid] = False

    def _vote_tally(self, y: bytes, x: bytes, ry: int) -> Tuple[int, int]:
        yes = no = 0
        for c, wids in self.witnesses.get(ry - 1, {}).items():
            c_yes = c_no = False
            for w in wids:
                if self.strongly_sees(y, w):
                    if self._vote(w, x):
                        c_yes = True
                    else:
                        c_no = True
            s = self._stake_at(c, ry - 1)
            if c_yes:
                yes += s
            if c_no:
                no += s
        return yes, no

    def _vote(self, y: bytes, x: bytes) -> bool:
        key = (y, x)
        memo = self.votes.get(key)
        if memo is not None:
            return memo
        d = self.round[y] - self.round[x]
        if d <= 1:
            v = self.sees(y, x)
        else:
            ry = self.round[y]
            yes, no = self._vote_tally(y, x, ry)
            v = yes >= no
            if d % self.config.coin_period == 0 and not (
                3 * max(yes, no) > 2 * self._tot_at(ry - 1)
            ):
                v = bool(self.hg[y].coin_bit())
        self.votes[key] = v
        return v

    def decide_fame(self) -> None:
        C = self.config.coin_period
        for rx in sorted(self.wit_list):
            for x in self.wit_list[rx]:
                if self.famous[x] is not None:
                    continue
                for ry in range(
                    max(self._next_vote_round[x], rx + 2), self.max_round + 1
                ):
                    d = ry - rx
                    decided = False
                    if d % C != 0:
                        epoch = self.ledger.epoch_at(ry - 1)
                        for y in self.wit_list.get(ry, []):
                            yes, no = self._vote_tally(y, x, ry)
                            if 3 * max(yes, no) > 2 * epoch.total_stake:
                                self.famous[x] = yes >= no
                                self.fame_epoch_log.append(
                                    (x, ry, epoch.epoch_id)
                                )
                                decided = True
                                if self.famous[x] and rx <= self._frozen_round:
                                    self.horizon_violations += 1
                                    if self.metrics is not None:
                                        self.metrics.count(
                                            "consensus_horizon_violations"
                                        )
                                break
                    self._next_vote_round[x] = ry + 1
                    if decided:
                        break

    # --------------------------------------------- decided-tx application

    def find_order(self) -> None:
        before = len(self.consensus)
        super().find_order()
        self._process_decided_txs(before)
        if not self._restating:
            self._refresh_current_epoch()

    def _process_decided_txs(self, start: int) -> None:
        need_restate = False
        for x in self.consensus[start:]:
            tx = decode_tx(self.hg[x].d)
            if tx is None:
                continue
            act = self._activation_round(self.round_received[x])
            if self._restating:
                self._next_ledger = self._next_ledger.apply(tx, act, x)
                continue
            new = self.ledger.apply(tx, act, x)
            grew = not new.same_epochs(self.ledger)
            self.ledger = new
            if grew:
                self._sync_registry_with_ledger()
                if new.head.activation_round <= self.max_round:
                    # an already-assigned round falls under the new
                    # epoch: incremental adoption would be arrival-order
                    # dependent — restate from scratch instead
                    need_restate = True
        if need_restate:
            self._restate()

    def _sync_registry_with_ledger(self) -> None:
        """Adopt the ledger's union registry as the decided member list
        (gossip surface + fork budget); newly decided members leave the
        pending set.  One member-axis extension == one repack."""
        registry = self.ledger.registry
        if len(registry) > len(self.members):
            self.repacks += 1
        for pk in registry:
            if pk not in self.member_index:
                self.member_index[pk] = len(self.members)
                self.members.append(pk)
                self._admit_gossip(pk)
                self.pending_members.pop(pk, None)

    def _refresh_current_epoch(self) -> None:
        epoch = self.ledger.epoch_at(self.max_round)
        self.stake = {m: epoch.stake_of(m) for m in self.members}
        self.tot_stake = epoch.total_stake

    # --------------------------------------------------------- restatement

    def _restate(self) -> None:
        """Deterministic full recompute to a ledger fixpoint.

        Iterates: freeze the candidate ledger, replay the whole DAG
        (rounds/fame/order) under it, collect the ledger its decided
        prefix implies; repeat until the epochs stabilize.  The result is
        a pure function of the DAG — independent of arrival order and of
        whether a peer got here incrementally."""
        if self._restating:
            return
        self._restating = True
        fin, self.finality = self.finality, None
        met, self.metrics = self.metrics, None
        rec, self.flightrec = self.flightrec, None
        try:
            current = self.ledger
            for _ in range(MAX_RESTATES):
                self._reset_consensus_state(current)
                self._next_ledger = EpochLedger.genesis(
                    self._genesis_members, self._genesis_stake
                )
                self.divide_rounds(list(self.order_added))
                self.decide_fame()
                self.find_order()
                new = self._next_ledger
                self._next_ledger = None
                stable = new.same_epochs(current)
                current = new
                self.ledger = new
                if stable:
                    break
            self.restatements += 1
        finally:
            self._restating = False
            self.finality = fin
            self.metrics = met
            self.flightrec = rec
        self._sync_registry_with_ledger()
        self._refresh_current_epoch()

    def _reset_consensus_state(self, ledger: EpochLedger) -> None:
        self.ledger = ledger
        self._sync_registry_with_ledger()
        self.round = {}
        self.is_witness = {}
        self.witnesses = {}
        self.wit_list = {}
        self.wit_slot = {}
        self._ss_memo = {}
        self.votes = {}
        self._next_vote_round = {}
        self.famous = {}
        self.fame_epoch_log = []
        self.max_round = 0
        self._frozen_round = -1
        self.late_witnesses = []
        self.horizon_violations = 0
        self.tbd = list(self.order_added)
        self.round_received = {}
        self.consensus_ts = {}
        self.consensus = []
        self.transactions = []
        self.consensus_round = 0

    # --------------------------------------------------------------- obs

    @property
    def membership_epoch(self) -> int:
        """Epoch id governing the node's current round frontier."""
        return self.ledger.epoch_at(self.max_round).epoch_id

    @property
    def members_active(self) -> int:
        return self.ledger.epoch_at(self.max_round).members_active

    @property
    def stake_total(self) -> int:
        return self.ledger.epoch_at(self.max_round).total_stake

    def state_digest(self) -> bytes:
        return crypto.hash_bytes(
            super().state_digest() + self.ledger.digest()
        )


def joining_node(
    sk: bytes,
    pk: bytes,
    network: Dict[bytes, Callable],
    registry: Sequence[bytes],
    config: Optional[SwirldConfig] = None,
    **kwargs,
) -> DynamicNode:
    """Bootstrap a node that is *not yet* in the decided registry: it
    self-admits for gossip, mints its genesis, and gains stake only once
    some registry member's JOIN transaction for it decides and
    activates."""
    config = config or SwirldConfig(n_members=len(registry))
    return DynamicNode(
        sk=sk, pk=pk, network=network, members=registry, config=config,
        **kwargs,
    )
