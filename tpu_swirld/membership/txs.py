"""Membership transaction wire format (``MTX1``).

Join/leave/restake requests ride ordinary event payloads, so they flow
through gossip, ordering, and the decided log exactly like application
transactions — a membership change is "decided" precisely when the round
containing its carrier event is fame-complete and the event is assigned
a ``round_received``.  The format is deliberately tiny and fixed-layout
(no pickle, no varints beyond the one length byte for the key):

    ``b"MTX1" + kind(1) + keylen(1) + pk(keylen) + stake(u32 LE)``

``stake`` is meaningful for JOIN (initial stake) and RESTAKE (new
stake); LEAVE carries 0.  A payload either parses as exactly one
membership transaction or is treated as opaque application data —
:func:`decode_tx` never raises on foreign payloads.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Optional

MAGIC = b"MTX1"
JOIN = 1
LEAVE = 2
RESTAKE = 3

_KINDS = {JOIN: "join", LEAVE: "leave", RESTAKE: "restake"}

MAX_TX_STAKE = 0xFFFFFFFF


@dataclasses.dataclass(frozen=True)
class MembershipTx:
    """One decoded membership transaction."""

    kind: int          # JOIN | LEAVE | RESTAKE
    pk: bytes          # subject member public key
    stake: int         # JOIN: initial stake; RESTAKE: new stake; LEAVE: 0

    @property
    def kind_name(self) -> str:
        return _KINDS.get(self.kind, f"?{self.kind}")


def encode_tx(tx: MembershipTx) -> bytes:
    if tx.kind not in _KINDS:
        raise ValueError(f"unknown membership tx kind {tx.kind}")
    if not 0 <= tx.stake <= MAX_TX_STAKE:
        raise ValueError(f"stake {tx.stake} out of u32 range")
    if not 0 < len(tx.pk) <= 255:
        raise ValueError("bad member key length")
    return (
        MAGIC
        + bytes([tx.kind, len(tx.pk)])
        + tx.pk
        + struct.pack("<I", tx.stake)
    )


def decode_tx(payload: bytes) -> Optional[MembershipTx]:
    """Parse ``payload`` as a membership tx; ``None`` for foreign data.

    Tolerant by design (gossip payloads are arbitrary bytes), but strict
    once the magic matches: a payload that *claims* to be an MTX and is
    malformed is still ``None`` — a half-parsed membership change must
    never take effect.
    """
    if len(payload) < len(MAGIC) + 2 or not payload.startswith(MAGIC):
        return None
    kind = payload[4]
    klen = payload[5]
    if kind not in _KINDS or klen == 0:
        return None
    end = 6 + klen + 4
    if len(payload) != end:
        return None
    pk = payload[6 : 6 + klen]
    (stake,) = struct.unpack_from("<I", payload, 6 + klen)
    if kind == JOIN and stake == 0:
        return None           # a zero-stake join is a no-op by definition
    if kind == LEAVE and stake != 0:
        return None
    return MembershipTx(kind=kind, pk=pk, stake=int(stake))


def join_payload(pk: bytes, stake: int) -> bytes:
    return encode_tx(MembershipTx(JOIN, pk, stake))


def leave_payload(pk: bytes) -> bytes:
    return encode_tx(MembershipTx(LEAVE, pk, 0))


def restake_payload(pk: bytes, stake: int) -> bytes:
    return encode_tx(MembershipTx(RESTAKE, pk, stake))
