"""Epoch-versioned member sets: :class:`MemberEpoch` + :class:`EpochLedger`.

The member set is a consensus-decided quantity.  The ledger is an
append-only sequence of epochs derived purely from the decided prefix:
every decided membership transaction (``membership.txs``) schedules a
new epoch at a deterministic *activation round*, so every honest node
reconstructs bit-identical epochs from the same decided order.

Design invariants (load-bearing for the engines):

- **Union registry.**  ``epochs[k].members`` is always a *prefix* of
  ``epochs[k+1].members``: joins append, leaves zero the member's stake
  but never remove the row.  Member indices are therefore stable forever,
  which is what lets the device engines keep their member-indexed slabs
  (anc/sees, ssm columns, witness tables, fork-pair ledgers) across an
  epoch boundary — the repack pass only ever *appends* member rows and
  swaps the stake vector (``membership.repack``).
- **Functional updates.**  Ledgers are immutable; ``apply`` returns a new
  ledger.  The mc checker's structure-aware node clone shallow-copies
  unknown attributes, so aliasing a ledger between a node and its clone
  must be safe — it is, because no ledger is ever mutated in place.
- **Round-addressed.**  ``epoch_at(r)`` is the single source of truth for
  "whose stake governs round r".  Rounds below the first activation are
  governed by the genesis epoch; activation rounds are strictly
  increasing; transactions deciding in the same round merge into one
  epoch.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from tpu_swirld import crypto
from tpu_swirld.membership.txs import JOIN, LEAVE, RESTAKE, MembershipTx

#: default number of rounds between a membership tx's decision
#: (round_received of its carrier event) and the first round the new
#: epoch's stake governs.  Honest gossip decides fame 2-3 rounds behind
#: round assignment, so 4 keeps activations ahead of every assigned
#: round in the common case — the incremental engines then adopt the new
#: epoch without a restatement.
DEFAULT_DELAY = 4


def activation_round(round_received: int, delay: int) -> int:
    """Canonical activation rule: a tx decided in round ``r`` governs
    from round ``r + delay``.  Kept as a free function so the checker's
    mutation seam (an off-by-one here) is caught against this canonical
    form by the epoch-purity invariant."""
    return round_received + delay


@dataclasses.dataclass(frozen=True)
class MemberEpoch:
    """One epoch: an ordered member list + stake vector, governing all
    rounds in ``[activation_round, next epoch's activation)``."""

    epoch_id: int
    activation_round: int
    members: Tuple[bytes, ...]
    stake: Tuple[int, ...]

    def __post_init__(self):
        if len(self.members) != len(self.stake):
            raise ValueError("epoch members/stake length mismatch")

    @property
    def total_stake(self) -> int:
        return sum(self.stake)

    @property
    def members_active(self) -> int:
        return sum(1 for s in self.stake if s > 0)

    def stake_of(self, pk: bytes) -> int:
        try:
            return self.stake[self.members.index(pk)]
        except ValueError:
            return 0

    def digest(self) -> bytes:
        parts: List[bytes] = [
            b"EPOCH",
            self.epoch_id.to_bytes(4, "little"),
            self.activation_round.to_bytes(4, "little", signed=True),
            len(self.members).to_bytes(4, "little"),
        ]
        for m, s in zip(self.members, self.stake):
            parts.append(len(m).to_bytes(1, "little"))
            parts.append(m)
            parts.append(int(s).to_bytes(8, "little"))
        return crypto.hash_bytes(b"".join(parts))


@dataclasses.dataclass(frozen=True)
class EpochLedger:
    """Append-only epoch sequence (ascending, distinct activations)."""

    epochs: Tuple[MemberEpoch, ...]
    applied: frozenset = frozenset()   # carrier event ids already applied

    # ------------------------------------------------------------ build

    @classmethod
    def genesis(
        cls, members: Sequence[bytes], stake: Sequence[int]
    ) -> "EpochLedger":
        return cls(
            epochs=(
                MemberEpoch(
                    epoch_id=0,
                    activation_round=0,
                    members=tuple(members),
                    stake=tuple(int(s) for s in stake),
                ),
            ),
        )

    # ----------------------------------------------------------- lookup

    @property
    def head(self) -> MemberEpoch:
        """The newest (possibly not-yet-active) epoch."""
        return self.epochs[-1]

    @property
    def registry(self) -> Tuple[bytes, ...]:
        """The union member registry (the newest epoch's member list —
        a superset of every older epoch's by the prefix invariant)."""
        return self.epochs[-1].members

    def epoch_at(self, r: int) -> MemberEpoch:
        """The epoch governing round ``r``."""
        cur = self.epochs[0]
        for e in self.epochs[1:]:
            if e.activation_round > r:
                break
            cur = e
        return cur

    def stake_at(self, pk: bytes, r: int) -> int:
        return self.epoch_at(r).stake_of(pk)

    def total_at(self, r: int) -> int:
        return self.epoch_at(r).total_stake

    # ------------------------------------------------------------ apply

    def apply(
        self,
        tx: MembershipTx,
        activation: int,
        carrier: bytes,
    ) -> "EpochLedger":
        """Apply one decided membership tx, scheduling (or merging into)
        the epoch at ``max(activation, head activation)``.  Idempotent
        per carrier event; no-op transactions (re-join of a known key,
        leave/restake of an inactive one) return ``self`` unchanged —
        first-decided-wins."""
        if carrier in self.applied:
            return self
        head = self.epochs[-1]
        members = list(head.members)
        stake = list(head.stake)
        if tx.kind == JOIN:
            if tx.pk in head.members:
                return self._mark(carrier)
            members.append(tx.pk)
            stake.append(int(tx.stake))
        elif tx.kind == LEAVE:
            try:
                i = members.index(tx.pk)
            except ValueError:
                return self._mark(carrier)
            if stake[i] == 0:
                return self._mark(carrier)
            stake[i] = 0
        elif tx.kind == RESTAKE:
            try:
                i = members.index(tx.pk)
            except ValueError:
                return self._mark(carrier)
            if stake[i] == 0 or stake[i] == int(tx.stake):
                return self._mark(carrier)
            stake[i] = int(tx.stake)
        else:
            return self._mark(carrier)
        act = max(int(activation), head.activation_round)
        if act == head.activation_round and len(self.epochs) > 1:
            # same-round decisions merge into the pending epoch
            new_epoch = MemberEpoch(
                epoch_id=head.epoch_id,
                activation_round=act,
                members=tuple(members),
                stake=tuple(stake),
            )
            epochs = self.epochs[:-1] + (new_epoch,)
        else:
            if act <= head.activation_round:
                act = head.activation_round + 1
            new_epoch = MemberEpoch(
                epoch_id=head.epoch_id + 1,
                activation_round=act,
                members=tuple(members),
                stake=tuple(stake),
            )
            epochs = self.epochs + (new_epoch,)
        return EpochLedger(epochs=epochs, applied=self.applied | {carrier})

    def _mark(self, carrier: bytes) -> "EpochLedger":
        return EpochLedger(
            epochs=self.epochs, applied=self.applied | {carrier}
        )

    # ------------------------------------------------------- comparison

    def digest(self) -> bytes:
        """Canonical digest over all epochs (checkpoint integrity: a
        restored node re-derives the ledger from the decided prefix and
        refuses a checkpoint whose epoch digest disagrees)."""
        return crypto.hash_bytes(b"LEDGER" + b"".join(
            e.digest() for e in self.epochs
        ))

    def same_epochs(self, other: "EpochLedger") -> bool:
        return self.epochs == other.epochs

    # ------------------------------------------------------ persistence

    def to_meta(self) -> dict:
        return {
            "epochs": [
                {
                    "epoch_id": e.epoch_id,
                    "activation_round": e.activation_round,
                    "members": [m.hex() for m in e.members],
                    "stake": list(e.stake),
                }
                for e in self.epochs
            ],
            "digest": self.digest().hex(),
        }

    @classmethod
    def from_meta(cls, meta: dict) -> "EpochLedger":
        epochs = tuple(
            MemberEpoch(
                epoch_id=int(d["epoch_id"]),
                activation_round=int(d["activation_round"]),
                members=tuple(bytes.fromhex(m) for m in d["members"]),
                stake=tuple(int(s) for s in d["stake"]),
            )
            for d in meta["epochs"]
        )
        ledger = cls(epochs=epochs)
        if meta.get("digest") and ledger.digest().hex() != meta["digest"]:
            raise ValueError("epoch ledger digest mismatch")
        return ledger


def ledger_from_decided(
    decided: Iterable[Tuple[bytes, bytes, int]],
    genesis_members: Sequence[bytes],
    genesis_stake: Sequence[int],
    delay: int = DEFAULT_DELAY,
) -> EpochLedger:
    """Canonical ledger reconstruction from a decided prefix.

    ``decided`` yields ``(event_id, payload, round_received)`` in
    consensus order.  This is the independent reconstruction path the
    epoch-purity invariant checks a live node's ledger against — it uses
    only the canonical :func:`activation_round` rule, so any activation
    skew in the live node's incremental path is a detectable divergence.
    """
    from tpu_swirld.membership.txs import decode_tx

    ledger = EpochLedger.genesis(genesis_members, genesis_stake)
    for eid, payload, r_received in decided:
        tx = decode_tx(payload)
        if tx is None:
            continue
        ledger = ledger.apply(
            tx, activation_round(r_received, delay), eid
        )
    return ledger
